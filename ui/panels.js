/* room_tpu dashboard panels (reference: src/ui/components/ —
   SwarmPanel, RoomsPanel, WorkersPanel, TasksPanel, MemoryPanel,
   SkillsPanel, MessagesPanel, VotesPanel, TransactionsPanel,
   ClerkPanel, SettingsPanel/StatusPanel — rebuilt dependency-free).
   Each panel: {title, render(el)}; live updates ride wsHandlers. */
"use strict";

let selectedRoom = null;

// ---- swarm (live view over cycle events; reference:
// SwarmPanel.tsx + hooks/useSwarmEvents.ts) ----

const swarmState = {cards: {}, logs: {}, focus: null};

wsHandlers.swarm = (msg) => {
  const m = /^room:(\d+)$/.exec(msg.channel || "");
  if (m) {
    const d = msg.data || {};
    if (msg.type === "cycle:started") {
      const prev = swarmState.cards[d.worker_id] || {};
      swarmState.cards[d.worker_id] = {...prev,
        status: "cycling", cycle: d.cycle_id, at: Date.now()};
      subscribe(`cycle:${d.cycle_id}`);
      swarmState.logs[d.cycle_id] = [];
    } else if (msg.type === "cycle:finished" || msg.type === "cycle:error") {
      for (const [wid, card] of Object.entries(swarmState.cards)) {
        if (card.cycle === d.cycle_id || msg.type === "cycle:error" &&
            String(d.worker_id) === wid) {
          card.status = msg.type === "cycle:error" ? "err"
            : (d.status === "error" ? "err" : "idle");
          card.last = d.status || d.error || "";
          if (d.duration_ms != null) card.duration_ms = d.duration_ms;
          if (d.output_tokens != null) card.tokens = d.output_tokens;
          card.cycles = (card.cycles || 0) + 1;
          // keep only the focused worker's finished-cycle logs
          if (swarmState.focus !== Number(wid)) {
            delete swarmState.logs[card.cycle];
            unsubscribe(`cycle:${card.cycle}`);
          }
        }
      }
    }
  }
  const c = /^cycle:(\d+)$/.exec(msg.channel || "");
  if (c && msg.type === "cycle:log") {
    const logs = swarmState.logs[c[1]] || (swarmState.logs[c[1]] = []);
    logs.push(msg.data || {});
    if (logs.length > 200) logs.shift();
  }
  if ((m || c) && currentView === "swarm") renderSwarmCards();
};

async function renderSwarm(el) {
  el.innerHTML = `
    <div class="panel"><h2>swarm
      <button class="${swarmState.tab !== "graph" ? "act" : "ghost"}"
        onclick="swarmShowTab('cards')">cards</button>
      <button class="${swarmState.tab === "graph" ? "act" : "ghost"}"
        onclick="swarmShowTab('graph')">graph</button>
    </h2>
      <div class="dim" id="swarmSummary">loading…</div>
      <div id="swarmRooms" style="margin-top:.6rem"></div>
    </div>
    <div class="panel" id="swarmConsoleBox" style="display:none">
      <h2>live console <span class="dim" id="swarmConsoleWho"
        style="font-size:.6em"></span>
        <button class="ghost" onclick="swarmFocus(null)">close</button>
      </h2>
      <div class="log" id="swarmConsole" style="max-height:340px"></div>
    </div>
    <div class="panel"><h2>event feed</h2>
      <div class="log" id="eventLog"></div></div>`;
  const rooms = (await api("GET", "/api/rooms")).data || [];
  const workers = [];
  await Promise.all(rooms.map(async r => {
    const ws_ = (await api("GET", `/api/rooms/${r.id}/workers`)).data || [];
    ws_.forEach(w => workers.push({...w, room_name: r.name}));
    subscribe(`room:${r.id}`);
  }));
  swarmState.workers = workers;
  swarmState.rooms = rooms;
  $("swarmSummary").textContent =
    `${rooms.length} rooms · ${workers.length} workers · ` +
    `${rooms.filter(r => r.launched).length} running`;
  renderSwarmCards();
  renderEventFeed();
}

function swarmFocus(workerId) {
  swarmState.focus = workerId;
  renderSwarmCards();
}

async function swarmRoomAction(roomId, action) {
  await api("POST", `/api/rooms/${roomId}/${action}`);
  showView("swarm");
}

function swarmShowTab(tab) {
  swarmState.tab = tab;
  showView("swarm");
}

function renderSwarmCards() {
  const grid = $("swarmRooms");
  if (!grid) return;
  if (swarmState.tab === "graph") { renderSwarmGraph(grid); return; }
  const workers = swarmState.workers || [];
  const rooms = swarmState.rooms || [];
  grid.innerHTML = rooms.map(r => {
    const team = workers.filter(w => w.room_id === r.id);
    return `<div style="margin-bottom:.8rem">
      <div class="row" style="align-items:center;margin:.2rem 0">
        <b>${esc(r.name)}</b>
        <span class="pill ${r.launched ? "running" : "stopped"}">
          ${r.launched ? "running" : "stopped"}</span>
        <button class="ghost" onclick="swarmRoomAction(${r.id},
          '${r.launched ? "stop" : "start"}')">
          ${r.launched ? "stop" : "start"}</button>
      </div>
      <div class="swarm-grid">${team.map(w =>
        swarmCard(w)).join("") ||
        '<div class="dim">no workers in this room yet</div>'}
      </div></div>`;
  }).join("") ||
    '<div class="dim">no workers yet — create a room first</div>';
  renderSwarmConsole();
  renderEventFeed();
}

function swarmCard(w) {
  const card = swarmState.cards[w.id] || {};
  const cls = card.status === "cycling" ? "cycling"
    : card.status === "err" ? "err" : "";
  const logs = (swarmState.logs[card.cycle] || []).slice(-4);
  const stats = [];
  if (card.duration_ms != null) {
    stats.push(`${(card.duration_ms / 1000).toFixed(1)}s`);
  }
  if (card.tokens != null) stats.push(`${card.tokens} tok`);
  if (card.cycles) stats.push(`${card.cycles} cycles live`);
  return `<div class="swarm-card ${cls}"
      onclick="swarmFocus(${w.id})" style="cursor:pointer">
    <div class="who">${esc(w.name)}
      ${w.is_default ? "👑" : ""}
      <span class="pill">${esc(w.role || "worker")}</span></div>
    <div class="dim" style="font-size:.8em">
      ${esc(card.status || w.agent_state || "idle")}
      ${stats.length ? " · " + stats.join(" · ") : ""}</div>
    ${w.wip ? `<div class="dim" style="font-size:.78em">
      WIP: ${esc(String(w.wip).slice(0, 90))}</div>` : ""}
    <div class="what">${logs.map(l =>
      `[${esc(l.entry_type)}] ${esc(String(l.content).slice(0, 160))}`
    ).join("\n") || esc(card.last || "")}</div>
  </div>`;
}

function renderSwarmGraph(grid) {
  // live graph view (reference: SwarmPanel.tsx's node/edge viz over
  // useSwarmEvents): queen at the hub, workers on a ring, edges light
  // up while a worker is mid-cycle
  const workers = swarmState.workers || [];
  const rooms = swarmState.rooms || [];
  grid.innerHTML = rooms.map(r => {
    const team = workers.filter(w => w.room_id === r.id);
    if (!team.length) return "";
    const queen = team.find(w => w.is_default) || team[0];
    const rest = team.filter(w => w !== queen);
    const W = 460, H = Math.max(240, 120 + rest.length * 26);
    const cx = W / 2, cy = H / 2;
    const rad = Math.min(cx, cy) - 52;
    const pos = {};
    pos[queen.id] = [cx, cy];
    rest.forEach((w, i) => {
      const a = (2 * Math.PI * i) / Math.max(rest.length, 1)
        - Math.PI / 2;
      pos[w.id] = [cx + rad * Math.cos(a), cy + rad * Math.sin(a)];
    });
    const edge = (w) => {
      const card = swarmState.cards[w.id] || {};
      const [x1, y1] = pos[queen.id], [x2, y2] = pos[w.id];
      return `<line class="swarm-graph-edge
        ${card.status === "cycling" ? "cycling" : ""}"
        x1="${x1}" y1="${y1}" x2="${x2}" y2="${y2}"/>`;
    };
    const node = (w) => {
      const card = swarmState.cards[w.id] || {};
      const [x, y] = pos[w.id];
      const cls = card.status === "cycling" ? "cycling"
        : card.status === "err" ? "err" : "";
      const sub = card.status || w.agent_state || "idle";
      return `<g class="swarm-graph-node ${cls}"
          onclick="swarmFocus(${w.id})">
        <circle cx="${x}" cy="${y}" r="${w === queen ? 26 : 20}"/>
        <text x="${x}" y="${y - 2}">${esc(w.name.slice(0, 10))}
          ${w === queen ? "👑" : ""}</text>
        <text x="${x}" y="${y + 12}" class="dim"
          style="font-size:9px;fill:var(--dim)">
          ${esc(String(sub).slice(0, 12))}</text>
      </g>`;
    };
    return `<div style="margin-bottom:.8rem">
      <div class="row" style="align-items:center;margin:.2rem 0">
        <b>${esc(r.name)}</b>
        <span class="pill ${r.launched ? "running" : "stopped"}">
          ${r.launched ? "running" : "stopped"}</span>
      </div>
      <svg width="${W}" height="${H}"
        viewBox="0 0 ${W} ${H}" style="max-width:100%">
        ${rest.map(edge).join("")}
        ${rest.map(node).join("")}
        ${node(queen)}
      </svg></div>`;
  }).join("") ||
    '<div class="dim">no workers yet — create a room first</div>';
  renderSwarmConsole();
  renderEventFeed();
}

function renderSwarmConsole() {
  const box = $("swarmConsoleBox");
  if (!box) return;
  const wid = swarmState.focus;
  if (!wid) { box.style.display = "none"; return; }
  const w = (swarmState.workers || []).find(x => x.id === wid) || {};
  const card = swarmState.cards[wid] || {};
  const logs = swarmState.logs[card.cycle] || [];
  box.style.display = "";
  $("swarmConsoleWho").textContent =
    `${w.name || "#" + wid} · cycle ${card.cycle || "—"}`;
  const el = $("swarmConsole");
  el.innerHTML = logs.map(l =>
    `<div><span class="t">${esc(l.entry_type)}</span>` +
    `${esc(String(l.content).slice(0, 800))}</div>`).join("") ||
    '<div class="dim">no live logs yet — trigger a cycle</div>';
  el.scrollTop = el.scrollHeight;
}

function renderEventFeed() {
  const log = $("eventLog");
  if (!log) return;
  log.innerHTML = wsLog.slice(-120).reverse().map(m =>
    `<div><span class="t">${esc(m.channel)}</span>${esc(m.type)} ` +
    `${esc(JSON.stringify(m.data) || "")}</div>`).join("");
}

// ---- rooms ----

async function renderRooms(el) {
  el.innerHTML = `<div class="cols">
    <div>
      <div class="panel"><h2>rooms</h2>
        <div id="roomList"></div>
        <div class="row">
          <input id="newRoomName" placeholder="new room name…">
          <button class="act" onclick="createRoom()">create</button>
        </div>
        <div class="row">
          <select id="roomTemplate"></select>
          <button class="ghost" onclick="instantiateTemplate()">
            from template</button>
        </div>
      </div>
    </div>
    <div id="roomDetail" class="panel"><h2>room</h2>
      <div class="dim">select a room</div></div>
  </div>`;
  loadRoomList();
  const t = (await api("GET", "/api/templates")).data || {};
  $("roomTemplate").innerHTML = (t.rooms || []).map(x =>
    `<option value="${esc(x.key)}">${esc(x.name)}</option>`).join("");
  if (selectedRoom) selectRoom(selectedRoom);
}

async function loadRoomList() {
  const out = await api("GET", "/api/rooms");
  const list = $("roomList");
  if (!list) return;
  list.innerHTML = (out.data || []).map(r => `
    <div class="card ${r.id === selectedRoom ? "sel" : ""}"
         onclick="selectRoom(${r.id})">
      <span class="name">#${r.id} ${esc(r.name)}</span>
      <span class="pill ${esc(r.status)}">${esc(r.status)}</span>
      ${r.launched ? '<span class="pill active">running</span>' : ""}
      <div class="meta">${esc(r.goal || "no objective")}</div>
    </div>`).join("") || '<div class="dim">no rooms yet</div>';
}

async function createRoom() {
  const name = $("newRoomName").value.trim();
  if (!name) return;
  await api("POST", "/api/rooms", {name, workerModel: "tpu"});
  $("newRoomName").value = "";
  loadRoomList();
}

async function instantiateTemplate() {
  const key = $("roomTemplate").value;
  if (!key) return;
  await api("POST", "/api/templates/instantiate", {template: key});
  loadRoomList();
}

async function selectRoom(id) {
  selectedRoom = id;
  loadRoomList();
  const [st, goals, decisions, chat, creds] = await Promise.all([
    api("GET", `/api/rooms/${id}/status`),
    api("GET", `/api/rooms/${id}/goals`),
    api("GET", `/api/rooms/${id}/decisions`),
    api("GET", `/api/rooms/${id}/chat`),
    api("GET", `/api/rooms/${id}/credentials`),
  ]);
  const s = st.data || {};
  const renderGoal = (g, depth) =>
    `<tr><td style="padding-left:${depth * 14 + 4}px">` +
    `${esc(g.description)}</td><td>${Math.round(g.progress * 100)}%` +
    `</td><td>${esc(g.status)}</td>` +
    `<td><button class="ghost" onclick="goalAction(${g.id},'complete')">
       done</button></td></tr>` +
    (g.children || []).map(c => renderGoal(c, depth + 1)).join("");
  $("roomDetail").innerHTML = `
    <h2>#${id} ${esc(s.room?.name)}
      <span class="pill ${esc(s.room?.status)}">${esc(s.room?.status)}
      </span></h2>
    <div class="row" style="margin:.2rem 0 .8rem">
      <button class="act" onclick="roomAction(${id},'start')">start</button>
      <button class="ghost" onclick="roomAction(${id},'stop')">stop</button>
      <button class="ghost" onclick="roomAction(${id},'pause')">pause</button>
      <span class="status dim" style="align-self:center">
        ${s.worker_count} workers · ${s.active_goals} goals ·
        ${s.open_decisions} open decisions ·
        ${s.pending_escalations} escalations</span>
    </div>
    <h2>goal tree</h2>
    <table>${(goals.data || []).map(g => renderGoal(g, 0)).join("")}</table>
    <div class="row">
      <input id="newGoal" placeholder="add a goal…">
      <button class="ghost" onclick="addGoal(${id})">add</button>
    </div>
    <h2 style="margin-top:.8rem">decisions</h2>
    <table>${(decisions.data || []).slice(0, 8).map(d => `
      <tr><td>${esc(d.proposal)}</td>
      <td><span class="pill">${esc(d.status)}</span></td></tr>`
    ).join("")}</table>
    <h2 style="margin-top:.8rem">credentials</h2>
    <table>${(creds.data || []).map(c => `
      <tr><td><code>${esc(c.name)}</code></td>
      <td class="dim">${esc(c.type || "other")}</td>
      <td style="width:4rem"><button class="ghost"
        onclick="credDelete(${id},'${esc(c.name)}')">remove</button>
      </td></tr>`).join("") ||
      '<tr><td class="dim">none stored</td></tr>'}</table>
    <div class="row">
      <input id="credName" placeholder="name (e.g. api_key)">
      <input id="credValue" placeholder="secret value" type="password">
      <button class="ghost" onclick="credAdd(${id})">store</button>
    </div>
    <h2 style="margin-top:.8rem">room settings</h2>
    ${(() => {
      const r = s.room || {};
      let cfg = {};
      try { cfg = JSON.parse(r.config || "{}"); } catch {}
      // stash for save: unknown config keys must survive a panel save,
      // and a blank/invalid gap must keep the current value
      roomDetailCtx = {cfg, gapMs: r.queen_cycle_gap_ms ?? 1800000};
      const sel = (id_, opts, cur) => `<select id="${id_}">${opts.map(o =>
        `<option value="${o}"${String(cur) === String(o)
          ? " selected" : ""}>${o}</option>`).join("")}</select>`;
      return `
      <div class="kv">
        <span class="k">name</span>
          <input id="roomNameEdit" value="${esc(r.name || "")}">
        <span class="k">objective</span>
          <input id="roomGoalEdit" value="${esc(r.goal || "")}">
        <span class="k">autonomy</span>
          ${sel("roomAutonomy", ["full", "semi", "manual"],
                r.autonomy_mode || "full")}
        <span class="k">visibility</span>
          ${sel("roomVisibility", ["private", "public"],
                r.visibility || "private")}
        <span class="k">worker model</span>
          <input id="roomWorkerModel"
                 value="${esc(r.worker_model || "tpu")}">
        <span class="k">queen nickname</span>
          <input id="roomNickname"
                 value="${esc(r.queen_nickname || "")}">
        <span class="k">cycle gap (min)</span>
          <input id="roomCycleGap" type="number" min="0.05" step="any"
                 value="${(r.queen_cycle_gap_ms ?? 1800000) / 60000}">
        <span class="k">max turns / cycle</span>
          <input id="roomMaxTurns" type="number" min="1"
                 value="${r.queen_max_turns ?? 50}">
        <span class="k">quiet hours</span>
          <span class="row" style="margin:0">
            <input id="roomQuietFrom" type="time"
                   value="${esc(r.queen_quiet_from || "")}">
            <input id="roomQuietUntil" type="time"
                   value="${esc(r.queen_quiet_until || "")}">
          </span>
        <span class="k">parallel tasks</span>
          <input id="roomMaxTasks" type="number" min="1" max="10"
                 value="${r.max_concurrent_tasks ?? 3}">
        <span class="k">vote threshold</span>
          ${sel("cfgThreshold",
                ["majority", "two_thirds", "unanimous"],
                cfg.voteThreshold || "majority")}
        <span class="k">vote timeout (min)</span>
          <input id="cfgVoteTimeout" type="number" min="1"
                 value="${cfg.voteTimeoutMinutes ?? 10}">
        <span class="k">min voters</span>
          <input id="cfgMinVoters" type="number" min="0"
                 value="${cfg.minVoters ?? 0}">
        <span class="k">queen tie-breaker</span>
          <input id="cfgTieBreaker" type="checkbox"
                 ${cfg.queenTieBreaker !== false ? "checked" : ""}>
        <span class="k">sealed ballots</span>
          <input id="cfgSealed" type="checkbox"
                 ${cfg.sealedBallot ? "checked" : ""}>
        <span class="k">auto-approve low impact</span>
          <input id="cfgAutoApprove" type="checkbox"
                 ${(cfg.autoApprove || ["low_impact"])
                   .includes("low_impact") ? "checked" : ""}>
      </div>
      <div class="dim" id="roomCfgError" style="color:#ff9b9b"></div>
      <div class="row">
        <button class="act" onclick="roomConfigSave(${id})">
          save settings</button>
        <button class="ghost" onclick="roomArchive(${id})">
          archive room</button>
      </div>`;
    })()}
    <h2 style="margin-top:.8rem">chat with the queen</h2>
    <div class="log" id="roomChat">${(chat.data || []).map(m =>
      `<div><span class="t">${esc(m.role)}</span>${esc(m.content)}</div>`
    ).join("")}</div>
    <div class="row">
      <input id="chatInput" placeholder="message the queen…"
             onkeydown="if(event.key==='Enter')roomChatSend(${id})">
      <button class="act" onclick="roomChatSend(${id})">send</button>
    </div>`;
  const log = $("roomChat");
  if (log) log.scrollTop = log.scrollHeight;
  subscribe(`room:${id}`);
}

async function goalAction(id, action) {
  await api("POST", `/api/goals/${id}/${action}`);
  if (selectedRoom) selectRoom(selectedRoom);
}

async function addGoal(id) {
  const input = $("newGoal");
  if (!input.value.trim()) return;
  await api("POST", `/api/rooms/${id}/goals`,
    {description: input.value.trim()});
  selectRoom(id);
}

async function roomAction(id, action) {
  await api("POST", `/api/rooms/${id}/${action}`);
  selectRoom(id);
}

async function credAdd(id) {
  const name = $("credName").value.trim();
  const value = $("credValue").value;
  if (!name || !value) return;
  await api("POST", `/api/rooms/${id}/credentials`, {name, value});
  selectRoom(id);
}

async function credDelete(id, name) {
  if (!await confirmDialog(`delete credential "${name}"?`, "delete")) {
    return;
  }
  await api("DELETE",
    `/api/rooms/${id}/credentials/${encodeURIComponent(name)}`);
  selectRoom(id);
}

let roomDetailCtx = {cfg: {}, gapMs: 1800000};

function roomConfigValidate() {
  // inline validation (reference: RoomSettingsPanel's per-field
  // checks): reject before the PUT, render why next to the button
  const errs = [];
  if (!$("roomNameEdit").value.trim()) {
    errs.push("name must not be empty");
  }
  const gap = parseFloat($("roomCycleGap").value);
  if ($("roomCycleGap").value.trim() !== "" &&
      !(gap > 0 && gap <= 24 * 60)) {
    errs.push("cycle gap must be between 0 and 1440 minutes");
  }
  const turns = parseInt($("roomMaxTurns").value, 10);
  if (!(turns >= 1 && turns <= 500)) {
    errs.push("max turns must be 1–500");
  }
  const tasks = parseInt($("roomMaxTasks").value, 10);
  if (!(tasks >= 1 && tasks <= 10)) {
    errs.push("parallel tasks must be 1–10");
  }
  const vt = parseInt($("cfgVoteTimeout").value, 10);
  if (!(vt >= 1 && vt <= 7 * 24 * 60)) {
    errs.push("vote timeout must be at least 1 minute");
  }
  const mv = parseInt($("cfgMinVoters").value, 10);
  if (!(mv >= 0 && mv <= 64)) {
    errs.push("min voters must be 0–64");
  }
  const from = $("roomQuietFrom").value.trim();
  const until = $("roomQuietUntil").value.trim();
  if (!!from !== !!until) {
    errs.push("quiet hours need both a from and an until time");
  }
  return errs;
}

async function roomConfigSave(id) {
  const errBox = $("roomCfgError");
  const errs = roomConfigValidate();
  if (errs.length) {
    if (errBox) errBox.textContent = errs.join(" · ");
    return;
  }
  if (errBox) errBox.textContent = "";
  const gapMin = parseFloat($("roomCycleGap").value);
  await api("PUT", `/api/rooms/${id}`, {
    name: $("roomNameEdit").value.trim(),
    goal: $("roomGoalEdit").value.trim(),
    autonomyMode: $("roomAutonomy").value,
    visibility: $("roomVisibility").value,
    workerModel: $("roomWorkerModel").value.trim() || "tpu",
    queenNickname: $("roomNickname").value.trim(),
    // blank or non-positive input keeps the stored gap (0 would make
    // the loop spin back-to-back cycles)
    queenCycleGapMs: gapMin > 0 ? Math.round(gapMin * 60000)
      : roomDetailCtx.gapMs,
    queenMaxTurns: parseInt($("roomMaxTurns").value, 10) || 50,
    queenQuietFrom: $("roomQuietFrom").value.trim() || null,
    queenQuietUntil: $("roomQuietUntil").value.trim() || null,
    maxConcurrentTasks: parseInt($("roomMaxTasks").value, 10) || 3,
    // spread the loaded config so keys this panel doesn't render
    // (e.g. minVoterHealth) survive a save
    config: {
      ...roomDetailCtx.cfg,
      voteThreshold: $("cfgThreshold").value,
      voteTimeoutMinutes:
        parseInt($("cfgVoteTimeout").value, 10) || 10,
      minVoters: parseInt($("cfgMinVoters").value, 10) || 0,
      queenTieBreaker: $("cfgTieBreaker").checked,
      sealedBallot: $("cfgSealed").checked,
      autoApprove: $("cfgAutoApprove").checked ? ["low_impact"] : [],
    },
  });
  selectRoom(id);
}

async function roomArchive(id) {
  if (!await confirmDialog(
    `archive room #${id}? Its loops stop and the room is removed ` +
    "from the swarm.", "archive")) return;
  await api("DELETE", `/api/rooms/${id}`);
  selectedRoom = null;
  refreshView();
}

async function roomChatSend(id) {
  const input = $("chatInput");
  if (!input.value.trim()) return;
  await api("POST", `/api/rooms/${id}/chat`, {content: input.value});
  input.value = "";
  selectRoom(id);
}

// ---- workers ----

async function renderWorkers(el) {
  const rooms = (await api("GET", "/api/rooms")).data || [];
  const blocks = await Promise.all(rooms.map(async r => {
    const ws_ = (await api("GET", `/api/rooms/${r.id}/workers`)).data || [];
    return `<div class="panel"><h2>${esc(r.name)}</h2>
      <table><tr><th>worker</th><th>role</th><th>model</th>
        <th>state</th><th>cycles</th><th></th></tr>
      ${ws_.map(w => `<tr>
        <td>#${w.id} ${esc(w.name)}</td><td>${esc(w.role || "")}</td>
        <td>${esc(w.model || "room default")}</td>
        <td><span class="pill">${esc(w.agent_state)}</span></td>
        <td>${w.cycle_count ?? ""}</td>
        <td><button class="ghost" onclick="triggerWorker(${w.id})">
          trigger</button></td></tr>`).join("")}</table>
      <div class="row">
        <input id="newWorker-${r.id}" placeholder="new worker name…">
        <button class="ghost" onclick="addWorker(${r.id})">add</button>
        <button class="ghost" onclick="promptsExport(${r.id})">
          export prompts</button>
        <button class="ghost" onclick="promptsImport(${r.id})">
          import prompts</button>
      </div><div id="promptSync-${r.id}" class="dim"
        style="font-size:.82em"></div></div>`;
  }));
  el.innerHTML = blocks.join("") ||
    '<div class="panel"><div class="dim">no rooms yet</div></div>';
}

async function promptsExport(roomId) {
  const out = await api("POST", `/api/rooms/${roomId}/prompts/export`);
  $(`promptSync-${roomId}`).textContent =
    "exported: " + ((out.data || {}).paths || []).join(", ");
}

async function promptsImport(roomId) {
  const out = await api("POST", `/api/rooms/${roomId}/prompts/import`, {});
  $(`promptSync-${roomId}`).textContent =
    "import: " + JSON.stringify(out.data || {});
}

async function triggerWorker(id) {
  await api("POST", `/api/workers/${id}/start`);
  refreshView();
}

async function addWorker(roomId) {
  const input = $(`newWorker-${roomId}`);
  if (!input.value.trim()) return;
  await api("POST", `/api/rooms/${roomId}/workers`,
    {name: input.value.trim()});
  refreshView();
}

// ---- tasks ----

async function renderTasks(el) {
  const out = await api("GET", "/api/tasks");
  el.innerHTML = `<div class="panel"><h2>tasks</h2>
    <table><tr><th>task</th><th>trigger</th><th>runs</th>
      <th>status</th><th></th></tr>
    ${(out.data || []).map(t => `
      <tr><td>#${t.id} ${esc(t.name)}
        <div class="dim" style="font-size:.82em">
          ${esc((t.prompt || "").slice(0, 110))}</div></td>
      <td>${esc(t.cron_expression || t.trigger_type)}</td>
      <td><a href="#" onclick="showRuns(${t.id});return false">
        ${t.run_count}</a></td>
      <td><span class="pill ${esc(t.status)}">${esc(t.status)}</span></td>
      <td class="row" style="margin:0">
        <button class="ghost" onclick="taskAction(${t.id},'run')">run</button>
        <button class="ghost" onclick="taskAction(${t.id},
          '${t.status === "paused" ? "resume" : "pause"}')">
          ${t.status === "paused" ? "resume" : "pause"}</button>
      </td></tr>`).join("")}</table>
    <div id="taskRuns"></div></div>`;
}

async function taskAction(id, action) {
  await api("POST", `/api/tasks/${id}/${action}`);
  refreshView();
}

async function showRuns(id) {
  const out = await api("GET", `/api/tasks/${id}/runs`);
  $("taskRuns").innerHTML = `<h2 style="margin-top:.8rem">
    runs of #${id}</h2>
    <table>${(out.data || []).slice(0, 10).map(r => `
      <tr><td>#${r.id}</td><td>${esc(when(r.started_at))}</td>
      <td><span class="pill ${esc(r.status)}">${esc(r.status)}</span></td>
      <td>${esc((r.result || r.error || "").slice(0, 150))}</td></tr>`
    ).join("")}</table>`;
}

// ---- memory ----

async function renderMemory(el) {
  if (memTab === "graph") {
    el.innerHTML = `<div class="panel"><h2>memory
      <button class="ghost" onclick="memShowTab('search')">search</button>
      <button class="act" onclick="memShowTab('graph')">graph</button>
      </h2><div id="memGraph"></div></div>`;
    renderMemoryGraph($("memGraph"));
    return;
  }
  el.innerHTML = `<div class="panel"><h2>memory
      <button class="act" onclick="memShowTab('search')">search</button>
      <button class="ghost" onclick="memShowTab('graph')">graph</button>
    </h2>
    <div class="row">
      <input id="memQuery" placeholder="search memories…"
        onkeydown="if(event.key==='Enter')memSearch()">
      <button class="act" onclick="memSearch()">search</button>
    </div>
    <div class="row">
      <input id="memNew" placeholder="remember something…">
      <button class="ghost" onclick="memAdd()">add</button>
    </div>
    <div id="memResults" style="margin-top:.6rem"></div></div>`;
  memSearch();
}

async function memSearch() {
  const q = $("memQuery") ? $("memQuery").value.trim() : "";
  const out = await api("GET",
    "/api/memory/search?q=" + encodeURIComponent(q || ""));
  $("memResults").innerHTML = `<table>
    ${(out.data || []).map(m => `
      <tr><td><b>${esc(m.name)}</b>
        ${esc((m.observations || []).join(" · ").slice(0, 220))}
        <div class="dim" style="font-size:.8em">
          ${esc(m.category || "")} · score ` +
          `${Number(m.score || 0).toFixed(4)}</div></td>
      <td style="width:4rem">
        <button class="ghost"
          onclick="memDelete(${m.entity_id})">forget</button>
      </td></tr>`).join("")}
  </table>` || '<div class="dim">nothing stored yet</div>';
}

async function memAdd() {
  const input = $("memNew");
  const content = input.value.trim();
  if (!content) return;
  await api("POST", "/api/memory",
    {name: content.slice(0, 48), content});
  input.value = "";
  memSearch();
}

async function memDelete(id) {
  if (!await confirmDialog(`delete memory #${id}?`, "delete")) return;
  await api("DELETE", `/api/memory/${id}`);
  memSearch();
}

// ---- skills ----

async function renderSkills(el) {
  const out = await api("GET", "/api/skills");
  el.innerHTML = `<div class="panel"><h2>skills</h2>
    <table>${(out.data || []).map(s => `
      <tr><td><b>${esc(s.name)}</b>
        <div class="dim" style="font-size:.84em">
          ${esc((s.content || s.description || "").slice(0, 160))}</div>
      </td>
      <td style="width:4rem">
        <button class="ghost" onclick="skillDelete(${s.id})">delete</button>
      </td></tr>`).join("")}</table>
    <div class="row">
      <input id="skillName" placeholder="skill name…">
      <input id="skillContent" placeholder="what was learned…">
      <button class="ghost" onclick="skillAdd()">add</button>
    </div></div>`;
}

async function skillAdd() {
  const name = $("skillName").value.trim();
  const content = $("skillContent").value.trim();
  if (!name || !content) return;
  await api("POST", "/api/skills", {name, content});
  refreshView();
}

async function skillDelete(id) {
  if (!await confirmDialog(`delete skill #${id}?`, "delete")) return;
  await api("DELETE", `/api/skills/${id}`);
  refreshView();
}

// ---- inbox (escalations + queen messages) ----

async function renderInbox(el) {
  const esc_ = (await api("GET", "/api/escalations")).data || [];
  const rooms = (await api("GET", "/api/rooms")).data || [];
  const msgBlocks = await Promise.all(rooms.map(async r => {
    const ms = (await api("GET", `/api/rooms/${r.id}/messages`)).data || [];
    return ms.filter(m => m.status === "unread")
             .map(m => ({...m, room: r.name}));
  }));
  const msgs = msgBlocks.flat();
  el.innerHTML = `
    <div class="panel"><h2>escalations</h2>
      <table>${esc_.filter(e => e.status === "pending").map(e => `
        <tr><td>${esc(e.question)}</td>
        <td style="min-width:16rem"><div class="row" style="margin:0">
          <input id="esc-${e.id}" placeholder="answer…">
          <button class="act" onclick="escAnswer(${e.id})">send</button>
          <button class="ghost" onclick="escDismiss(${e.id})">dismiss</button>
        </div></td></tr>`).join("") ||
        '<tr><td class="dim">nothing pending</td></tr>'}</table></div>
    <div class="panel"><h2>unread messages</h2>
      <table>${msgs.map(m => `
        <tr><td><span class="pill">${esc(m.room)}</span>
          <b>${esc(m.subject || "")}</b> ${esc(m.body || "")}</td>
        <td style="min-width:16rem"><div class="row" style="margin:0">
          <input id="msg-${m.id}" placeholder="reply…">
          <button class="act" onclick="msgReply(${m.id})">reply</button>
          <button class="ghost" onclick="msgRead(${m.id})">mark read</button>
        </div></td></tr>`).join("") ||
        '<tr><td class="dim">inbox zero</td></tr>'}</table></div>`;
}

async function escAnswer(id) {
  const v = $(`esc-${id}`).value.trim();
  if (!v) return;
  await api("POST", `/api/escalations/${id}/answer`, {answer: v});
  refreshView();
}

async function escDismiss(id) {
  await api("POST", `/api/escalations/${id}/dismiss`);
  refreshView();
}

async function msgReply(id) {
  const v = $(`msg-${id}`).value.trim();
  if (!v) return;
  await api("POST", `/api/messages/${id}/reply`, {body: v});
  refreshView();
}

async function msgRead(id) {
  await api("POST", `/api/messages/${id}/read`);
  refreshView();
}

// ---- votes ----

async function renderVotes(el) {
  const rooms = (await api("GET", "/api/rooms")).data || [];
  const blocks = await Promise.all(rooms.map(async r => {
    const ds = (await api("GET", `/api/rooms/${r.id}/decisions`)).data || [];
    const open = ds.filter(d => d.status === "announced" ||
                                d.status === "voting");
    if (!open.length) return "";
    return `<div class="panel"><h2>${esc(r.name)}</h2>
      <table>${open.map(d => `
        <tr><td>${esc(d.proposal)}
          <div class="dim" style="font-size:.8em">
            ${esc(when(d.created_at))}</div></td>
        <td><div class="row" style="margin:0">
          <button class="act"
            onclick="vote(${d.id},'approve')">approve</button>
          <button class="ghost"
            onclick="vote(${d.id},'reject')">reject</button>
          <button class="ghost"
            onclick="keeperVote(${d.id})">keeper veto</button>
        </div></td></tr>`).join("")}</table></div>`;
  }));
  el.innerHTML = blocks.join("") ||
    `<div class="panel"><div class="dim">no open decisions</div></div>`;
}

async function vote(id, v) {
  // the dashboard user IS the keeper: approve/reject ride the
  // keeper-vote route (worker ballots need a workerId and come from
  // agents/MCP, not this panel)
  await api("POST", `/api/decisions/${id}/keeper-vote`, {vote: v});
  refreshView();
}

async function keeperVote(id) {
  await api("POST", `/api/decisions/${id}/keeper-vote`, {vote: "reject"});
  refreshView();
}

// ---- wallet ----

async function renderWallet(el) {
  const rooms = (await api("GET", "/api/rooms")).data || [];
  const blocks = await Promise.all(rooms.map(async r => {
    const w = (await api("GET", `/api/rooms/${r.id}/wallet`)).data;
    if (!w) return "";
    const txs = (await api("GET",
      `/api/rooms/${r.id}/wallet/transactions`)).data || [];
    const ident = (await api("GET",
      `/api/rooms/${r.id}/identity`)).data;
    return `<div class="panel"><h2>${esc(r.name)} wallet</h2>
      <div class="kv">
        <span class="k">address</span><span>
          <code>${esc(w.address)}</code></span>
        <span class="k">chain</span><span>${esc(w.chain)}</span>
        <span class="k">identity</span>
        <span>${ident?.registered
          ? `<span class="pill verified">ERC-8004
              #${esc(ident.erc8004_agent_id)}</span>`
          : `<span class="dim">unregistered</span>
             <button class="ghost"
               onclick="identityRegister(${r.id})">
               prepare registration</button>`}</span>
      </div>
      <div class="row">
        <input id="wdTo-${r.id}" placeholder="0x recipient…">
        <input id="wdAmt-${r.id}" placeholder="amount (token units)">
        <button class="ghost" onclick="withdraw(${r.id})">withdraw</button>
      </div>
      <table style="margin-top:.5rem">${txs.slice(0, 8).map(t => `
        <tr><td>${esc(t.type)}</td><td>${esc(t.amount)}</td>
        <td>${esc(t.counterparty || "")}</td>
        <td><span class="pill ${esc(t.status)}">${esc(t.status)}</span>
        </td></tr>`).join("")}</table></div>`;
  }));
  el.innerHTML = blocks.join("") ||
    `<div class="panel"><div class="dim">
      no wallets — rooms create theirs on launch</div></div>`;
}

async function identityRegister(roomId) {
  const out = await api("POST",
    `/api/rooms/${roomId}/identity/register`, {dryRun: true});
  if (out.data?.tx) {
    toast(`registration tx prepared for ${out.data.tx.to}`);
  }
}

async function withdraw(roomId) {
  const to = $(`wdTo-${roomId}`).value.trim();
  const amount = $(`wdAmt-${roomId}`).value.trim();
  if (!to || !amount) return;
  const out = await api("POST", `/api/rooms/${roomId}/wallet/withdraw`,
    {to, amount});
  if (out.data?.txHash) toast(`sent: ${out.data.txHash}`);
  refreshView();
}

// ---- clerk ----

wsHandlers.clerk = (msg) => {
  if (msg.type === "clerk:commentary" && currentView === "clerk") {
    refreshView();
  }
};

// clerk setup guide (reference: ClerkSetupGuide.tsx — a step flow
// that takes the keeper from nothing-configured to a verified clerk
// turn; here: backend -> connect -> model -> test)
let clerkGuideStep = 0;   // 0 = closed

function clerkGuideOpen() {
  clerkGuideStep = 1;
  refreshView();
}

function clerkGuideClose() {
  clerkGuideStep = 0;
  refreshView();
}

async function clerkGuideHtml() {
  if (!clerkGuideStep) return "";
  const steps = ["backend", "connect", "model", "test"];
  const crumbs = steps.map((s, i) =>
    `<span class="pill ${i + 1 === clerkGuideStep ? "verified" : ""}">
      ${i + 1} · ${s}</span>`).join(" ");
  let body = "";
  if (clerkGuideStep === 1) {
    const ms = (await api("GET", "/api/models/status")).data || {};
    const tpuReady = Object.values(ms).some(m => m.ready);
    body = `<p class="dim">The clerk answers the keeper directly; it
      rides the first backend in its fallback chain that works. Pick
      what to set up:</p>
      <table>
        <tr><td>tpu (in-tree serving)</td>
          <td>${tpuReady
            ? '<span class="pill verified">weights ready</span>'
            : '<span class="pill pending">weights not loaded</span>'}
          </td></tr>
        <tr><td>CLI provider (claude / codex)</td>
          <td class="dim">uses your existing CLI login</td></tr>
        <tr><td>API provider (openai / anthropic / gemini)</td>
          <td class="dim">needs an API key in the environment</td></tr>
      </table>`;
  } else if (clerkGuideStep === 2) {
    const provs = (await api("GET", "/api/providers")).data || {};
    body = `<p class="dim">Connect a provider (skip if the tpu
      backend already shows ready):</p>
      <table>${Object.entries(provs).map(([key, p]) => `
        <tr><td>${esc(key)}</td>
        <td>${p.connected
          ? '<span class="pill verified">connected</span>'
          : p.installed
            ? '<span class="pill pending">not logged in</span>'
            : '<span class="pill pending">not installed</span>'}</td>
        <td>${p.connected ? "" : p.installed
          ? `<button class="ghost"
               onclick="provAuthStart('${esc(key)}')">log in</button>`
          : `<button class="ghost"
               onclick="provInstallStart('${esc(key)}')">install</button>`}
        </td></tr>`).join("")}</table>
      <p class="dim">Install/login sessions stream into the providers
        panel; come back here when a row shows connected.</p>`;
  } else if (clerkGuideStep === 3) {
    const cur = ((await api("GET", "/api/settings/clerk_model"))
      .data || {}).value || "";
    body = `<p class="dim">Preferred clerk model (first try in the
      fallback chain). Examples: <code>tpu:qwen3-coder-30b</code>,
      <code>claude:sonnet</code>, <code>openai:gpt-4o-mini</code>.</p>
      <div class="row">
        <input id="clerkModelPick" value="${esc(cur)}"
          placeholder="provider:model">
        <button class="act" onclick="clerkGuideSaveModel()">
          save</button>
      </div>`;
  } else {
    body = `<p class="dim">Send a test turn; a reply below means the
      clerk is live end-to-end.</p>
      <div class="row">
        <button class="act" onclick="clerkGuideTest()">
          send test message</button>
      </div>
      <div class="dim" id="clerkGuideTestOut"></div>`;
  }
  const nav = `<div class="row" style="margin-top:.6rem">
    ${clerkGuideStep > 1 ? `<button class="ghost"
      onclick="clerkGuideStep--;refreshView()">back</button>` : ""}
    ${clerkGuideStep < 4 ? `<button class="act"
      onclick="clerkGuideStep++;refreshView()">next</button>`
      : `<button class="act" onclick="clerkGuideClose()">
           done</button>`}
    <button class="ghost" onclick="clerkGuideClose()">close</button>
  </div>`;
  return `<div class="panel"><h2>clerk setup guide</h2>
    <div class="row">${crumbs}</div>${body}${nav}</div>`;
}

async function clerkGuideSaveModel() {
  const v = $("clerkModelPick").value.trim();
  await api("PUT", "/api/settings/clerk_model", {value: v});
  clerkGuideStep = 4;
  refreshView();
}

async function clerkGuideTest() {
  $("clerkGuideTestOut").textContent = "asking the clerk…";
  const out = await api("POST", "/api/clerk/message",
    {content: "setup check: reply with one short sentence."});
  $("clerkGuideTestOut").textContent =
    (out.data && (out.data.reply || out.data.content)) ||
    out.error || "no reply — check the providers panel";
}

async function renderClerk(el) {
  const out = await api("GET", "/api/clerk/messages");
  const st = (await api("GET", "/api/clerk/status")).data || {};
  const guide = await clerkGuideHtml();
  el.innerHTML = `${guide}<div class="panel"><h2>clerk
      <span class="dim" style="font-size:.6em">${st.messages || 0}
        messages · ${st.turns || 0} turns ·
        last ${esc(when(st.lastMessageAt) || "never")}</span>
      <button class="ghost" onclick="clerkGuideOpen()">
        setup guide</button>
      <button class="ghost" onclick="clerkReset()">reset</button></h2>
    <div class="log" id="clerkLog" style="max-height:460px">
      ${(out.data || []).map(m =>
        `<div><span class="t">${esc(m.role)}</span>${esc(m.content)}</div>`
      ).join("")}</div>
    <div class="row">
      <input id="clerkInput" placeholder="ask the clerk…"
        onkeydown="if(event.key==='Enter')clerkSend()">
      <button class="act" onclick="clerkSend()">send</button>
    </div></div>`;
  const log = $("clerkLog");
  if (log) log.scrollTop = log.scrollHeight;
}

async function clerkReset() {
  if (!await confirmDialog(
    "reset the clerk conversation?", "reset")) return;
  await api("POST", "/api/clerk/reset", {});
  refreshView();
}

async function clerkSend() {
  const input = $("clerkInput");
  if (!input.value.trim()) return;
  const text = input.value;
  input.value = "";
  $("clerkLog").innerHTML +=
    `<div><span class="t">user</span>${esc(text)}</div>`;
  await api("POST", "/api/clerk/message", {content: text});
  refreshView();
}

// ---- settings / status ----

async function renderSettings(el) {
  const [settings, providers, contactsOut, engines, status] =
    await Promise.all([
      api("GET", "/api/settings"),
      api("GET", "/api/providers"),
      api("GET", "/api/contacts/status"),
      api("GET", "/api/tpu/engines"),
      api("GET", "/api/status"),
    ]);
  const s = status.data || {};
  const c = contactsOut.data || {email: {}, telegram: {}};
  el.innerHTML = `
    <div class="panel"><h2>runtime</h2>
      <div class="kv">
        <span class="k">version</span><span>${esc(s.version)}</span>
        <span class="k">platform</span>
          <span>${esc(s.platform)} × ${esc(s.devices)}</span>
        <span class="k">active rooms</span><span>${esc(s.activeRooms)}</span>
      </div></div>
    <div class="panel"><h2>serving engines</h2>
      <table>${Object.entries(engines.data || {}).map(([name, e]) => `
        <tr><td>${esc(name)}</td>
        <td><span class="pill ${esc(e.status)}">${esc(e.status)}</span></td>
        <td>${e.tokens_decoded ?? ""} tok ·
            ${e.free_pages ?? ""} free pages ·
            ${e.evictions ?? 0} evictions</td></tr>`).join("") ||
        '<tr><td class="dim">no engines warm</td></tr>'}</table></div>
    <div class="panel"><h2>cli providers</h2>
      <table>${Object.entries(providers.data || {}).map(([name, p]) => `
        <tr><td>${esc(name)}</td>
        <td>${p.installed ? esc(p.version || "installed")
             : '<span class="dim">not installed</span>'}</td>
        <td>${p.connected === true
              ? '<span class="pill verified">connected</span>'
              : p.connected === false
                ? '<span class="pill pending">not authenticated</span>'
                : ""}</td>
        <td>${p.installed && p.connected === false
          ? `<button class="ghost" onclick="providerLogin('${esc(name)}')">
              login</button>` : ""}</td></tr>`).join("")}</table>
      <div id="providerAuth"></div></div>
    <div class="panel"><h2>contacts</h2>
      <div class="kv">
        <span class="k">email</span>
        <span>${esc(c.email.address || "not set")}
          ${c.email.verified ? '<span class="pill verified">verified</span>'
            : c.email.pendingCode
              ? '<span class="pill pending">code sent</span>' : ""}</span>
        <span class="k">telegram</span>
        <span>${c.telegram.connected
          ? `connected <span class="pill verified">
              ${esc(c.telegram.details?.username || "")}</span>`
          : '<span class="dim">not connected</span>'}</span>
      </div>
      <div class="row">
        <input id="contactEmail" placeholder="keeper email…">
        <button class="ghost" onclick="emailStart()">send code</button>
        <input id="contactCode" placeholder="6-digit code">
        <button class="ghost" onclick="emailVerify()">verify</button>
      </div>
      <div class="row">
        <button class="ghost" onclick="tgStart()">
          connect telegram</button>
        <span id="tgLink" class="dim"></span>
      </div>
      <div class="row" style="align-items:center">
        <span class="k">desktop notifications</span>
        ${typeof notifySupported === "function" && notifySupported()
          ? (notifyPermitted()
            ? '<span class="pill verified">enabled</span>'
            : `<button class="ghost" onclick="notifyRequest()">
                 enable</button>`)
          : '<span class="dim">not supported here</span>'}
        <span class="dim" style="font-size:.85em">
          escalations + new proposals alert even when this tab is in
          the background</span>
      </div></div>
    <div class="panel"><h2>settings</h2>
      <table id="settingsTable">${
        Object.entries(settings.data || {}).map(([k, v]) => `
        <tr><td>${esc(k)}</td><td>${esc(v)}</td></tr>`).join("")}
      </table>
      <div class="row">
        <input id="setKey" placeholder="key">
        <input id="setVal" placeholder="value">
        <button class="ghost" onclick="setSetting()">set</button>
      </div></div>`;
}

async function setSetting() {
  const k = $("setKey").value.trim();
  if (!k) return;
  await api("PUT", "/api/settings", {[k]: $("setVal").value});
  refreshView();
}

async function providerLogin(provider) {
  const out = await api("POST",
    `/api/providers/${provider}/auth/start`, {});
  const sid = out.data?.sessionId;
  if (!sid) return;
  const poll = async () => {
    const v = (await api("GET",
      `/api/providers/auth/sessions/${sid}`)).data;
    if (!v) return;
    $("providerAuth").innerHTML = `<div class="dim"
        style="margin-top:.5rem">
      ${esc(v.status)} ${v.verificationUrl
        ? `— visit <a href="${esc(v.verificationUrl)}" target="_blank"
            style="color:var(--accent)">${esc(v.verificationUrl)}</a>`
        : ""}
      ${v.deviceCode ? `— code <code>${esc(v.deviceCode)}</code>` : ""}
      <div>${v.lines.slice(-4).map(l => esc(l.text)).join("<br>")}</div>
    </div>`;
    if (v.active) setTimeout(poll, 1500);
    else refreshView();
  };
  poll();
}

async function emailStart() {
  const email = $("contactEmail").value.trim();
  if (!email) return;
  await api("POST", "/api/contacts/email/start", {email});
  toast("verification code sent");
}

async function emailVerify() {
  const code = $("contactCode").value.trim();
  if (!code) return;
  const out = await api("POST", "/api/contacts/email/verify", {code});
  if (out.data?.ok) refreshView();
}

async function tgStart() {
  const out = await api("POST", "/api/contacts/telegram/start", {});
  if (out.data?.deepLink) {
    $("tgLink").innerHTML = `open <a href="${esc(out.data.deepLink)}"
      target="_blank" style="color:var(--accent)">
      ${esc(out.data.deepLink)}</a>`;
  }
}

// ---- cycles (live console browser) ----

async function renderCycles(el) {
  const rooms = (await api("GET", "/api/rooms")).data || [];
  el.innerHTML = `<div class="panel"><h2>cycle browser</h2>
    <div class="row">
      <select id="cycleRoom" onchange="loadCycles()">
        ${rooms.map(r =>
          `<option value="${r.id}">${esc(r.name)}</option>`).join("")}
      </select>
      <button class="ghost" onclick="loadCycles()">load</button>
    </div>
    <div id="cycleList" style="margin-top:.6rem"></div>
    <div id="cycleLogs" style="margin-top:.6rem"></div></div>`;
  if (rooms.length) loadCycles();
}

async function loadCycles() {
  const rid = $("cycleRoom").value;
  if (!rid) return;
  const out = await api("GET", `/api/rooms/${rid}/cycles`);
  $("cycleList").innerHTML = `<table>
    <tr><th>cycle</th><th>worker</th><th>status</th><th>tokens</th>
    <th>ms</th><th></th></tr>
    ${(out.data || []).slice(0, 20).map(c => `
      <tr><td>#${c.id}</td><td>${esc(c.worker_id)}</td>
      <td><span class="pill ${esc(c.status)}">${esc(c.status)}</span></td>
      <td>${(c.input_tokens || 0) + (c.output_tokens || 0)}</td>
      <td>${c.duration_ms ?? ""}</td>
      <td><button class="ghost" onclick="loadCycleLogs(${c.id})">
        logs</button></td></tr>`).join("")}</table>`;
}

async function loadCycleLogs(cid) {
  const out = await api("GET", `/api/cycles/${cid}/logs`);
  $("cycleLogs").innerHTML = `<h2>cycle #${cid}</h2>
    <div class="log" style="max-height:420px">
      ${(out.data || []).map(l =>
        `<div><span class="t">${esc(l.entry_type)}</span>` +
        `${esc(String(l.content).slice(0, 600))}</div>`).join("")}
    </div>`;
}

// ---- system (self-mod audit, watches, updates) ----

async function renderSystem(el) {
  const [audit, watches, update, prof] = await Promise.all([
    api("GET", "/api/self-mod/audit"),
    api("GET", "/api/watches"),
    api("GET", "/api/update"),
    api("GET", "/api/profiling/http"),
  ]);
  const u = update.data || {};
  const auto = u.autoUpdate || {state: "idle"};
  el.innerHTML = `
    <div class="panel"><h2>updates</h2>
      <div class="kv">
        <span class="k">running</span>
          <span>v${esc(u.currentVersion)}</span>
        <span class="k">latest</span>
          <span>${esc(u.updateInfo?.latestVersion || "unknown")}</span>
        <span class="k">auto-update</span>
          <span><span class="pill ${esc(auto.state)}">
            ${esc(auto.state)}</span>
            ${auto.version ? esc(auto.version) : ""}</span>
      </div>
      <div class="row">
        <button class="ghost" onclick="updateCheck()">check now</button>
        ${auto.state === "ready"
          ? `<button class="act" onclick="updateRestart()">
              apply v${esc(auto.version)} + restart</button>`
          : ""}
        <button class="ghost" onclick="serverRestart()">restart</button>
      </div></div>
    <div class="panel"><h2>watched paths</h2>
      <table>${(watches.data || []).map(w => `
        <tr><td><code>${esc(w.path)}</code></td>
        <td>${esc(w.action_prompt || "")}</td>
        <td style="width:4rem"><button class="ghost"
          onclick="watchDelete(${w.id})">remove</button></td></tr>`
      ).join("")}</table>
      <div class="row">
        <input id="watchPath" placeholder="~/path/to/watch">
        <input id="watchPrompt" placeholder="what to do on change…">
        <button class="ghost" onclick="watchAdd()">watch</button>
      </div></div>
    <div class="panel"><h2>self-modification audit</h2>
      <table>${(audit.data || []).slice(0, 15).map(a => `
        <tr><td>#${a.id}</td><td><code>${esc(a.file_path)}</code></td>
        <td>${esc(a.reason || "")}</td>
        <td><span class="pill">${esc(a.status || "")}</span></td>
        <td style="width:4rem"><button class="ghost"
          onclick="selfmodRevert(${a.id})">revert</button></td></tr>`
      ).join("") ||
        '<tr><td class="dim">no self-modifications recorded</td></tr>'}
      </table></div>
    <div class="panel"><h2>http profiling
        <span class="dim" style="font-size:.6em">set
          ROOM_TPU_PROFILE_HTTP=1 to record</span></h2>
      <table><tr><th>endpoint</th><th>calls</th><th>mean ms</th>
        <th>p95 ms</th></tr>
      ${Object.entries(prof.data || {})
        .sort((a, b) => (b[1].count || 0) - (a[1].count || 0))
        .slice(0, 20).map(([k, p]) => `
        <tr><td><code>${esc(k)}</code></td>
        <td>${p.count || 0}</td>
        <td>${p.mean_ms ?? ""}</td>
        <td>${p.p95_ms ?? ""}</td>
        </tr>`).join("") ||
        '<tr><td class="dim">profiling off or no samples</td></tr>'}
      </table></div>
    <div class="panel"><h2>member invites</h2>
      <div class="row">
        <button class="ghost" onclick="inviteCreate()">
          mint member invite token</button></div>
      <pre class="log" id="inviteOut" style="display:none"></pre></div>`;
}

async function inviteCreate() {
  const out = await api("POST", "/api/invites", {});
  const el = $("inviteOut");
  el.style.display = "block";
  el.textContent = out.data?.token
    ? `member token (share with a collaborator):\n${out.data.token}`
    : (out.error || "invites disabled: set ROOM_TPU_CLOUD_JWT_SECRET");
}

async function updateCheck() {
  await api("POST", "/api/update/check", {ignoreBackoff: true});
  refreshView();
}

async function updateRestart() {
  if (!await confirmDialog(
    "apply the staged update and restart the server?",
    "update + restart")) return;
  // localhost-only pre-auth endpoint (no bearer token needed)
  await fetch("/api/server/update-restart", {method: "POST"});
  toast("applying update and restarting…");
}

async function serverRestart() {
  if (!await confirmDialog("restart the server?", "restart")) return;
  await fetch("/api/server/restart", {method: "POST"});
  toast("restarting…");
}

async function watchAdd() {
  const path = $("watchPath").value.trim();
  if (!path) return;
  await api("POST", "/api/watches",
    {path, actionPrompt: $("watchPrompt").value.trim()});
  refreshView();
}

async function watchDelete(id) {
  if (!await confirmDialog(`delete watch #${id}?`, "delete")) return;
  await api("DELETE", `/api/watches/${id}`);
  refreshView();
}

async function selfmodRevert(id) {
  if (!await confirmDialog(
    `revert self-modification #${id}?`, "revert")) return;
  await api("POST", `/api/self-mod/${id}/revert`, {});
  refreshView();
}

// ---- tpu (engines + weight provisioning) ----

wsHandlers.tpu = (msg) => {
  if (msg.channel === "tpu-model" && currentView === "tpu") {
    const log = $("provisionLog");
    if (log && msg.data?.line) {
      log.innerHTML += `<div>${esc(msg.data.line)}</div>`;
      log.scrollTop = log.scrollHeight;
    }
  }
};

async function renderTpu(el) {
  const [status, engines, models, health] = await Promise.all([
    api("GET", "/api/tpu/status"),
    api("GET", "/api/tpu/engines"),
    api("GET", "/api/models/status"),
    api("GET", "/api/tpu/health"),
  ]);
  const st = status.data || {};
  const hl = health.data || {};
  const DEGRADE_LABELS = ["healthy", "spec off", "offloading",
                          "batch shrunk", "shedding"];
  const mb = (b) => b == null ? "" : `${(b / 1048576).toFixed(1)}MB`;
  const histStr = (h) => Object.entries(h || {})
    .filter(([k, n]) => n > 0)
    .map(([k, n]) => `${k.replace("le_", "≤").replace("gt_", ">")}:${n}`)
    .join(" ") || "—";
  const healthPill = (e) => {
    if (e.healthy === false)
      return '<span class="pill failed">crash loop</span>';
    const phase = e.lifecycle?.phase;
    if (phase && phase !== "serving")
      return `<span class="pill pending">${esc(phase)}</span>`;
    const lvl = e.degradation_level || 0;
    return `<span class="pill ${lvl ? "pending" : "verified"}">` +
      `${esc(DEGRADE_LABELS[lvl] || lvl)}</span>`;
  };
  el.innerHTML = `
    <div class="panel"><h2>accelerator</h2>
      <div class="kv">
        <span class="k">platform</span><span>${esc(st.platform)}</span>
        <span class="k">devices</span><span>${esc(st.devices)}</span>
        <span class="k">ready</span>
          <span>${st.ready
            ? '<span class="pill verified">yes</span>'
            : `<span class="pill failed">no</span>
               <span class="dim">${esc(st.reason || "")}</span>`}</span>
      </div></div>
    <div class="panel"><h2>serving engines</h2>
      <table><tr><th>model</th><th>status</th><th>health</th>
        <th>decoded</th><th>prefill</th><th>sessions</th>
        <th>free pages</th><th>evictions</th></tr>
      ${Object.entries(engines.data || {}).map(([name, e]) => `
        <tr><td>${esc(name)}</td>
        <td><span class="pill ${esc(e.status)}">${esc(e.status)}</span>
        </td>
        <td>${healthPill(e)}</td>
        <td>${e.tokens_decoded ?? ""}</td>
        <td>${e.prefill_tokens ?? ""}</td>
        <td>${e.sessions ?? ""}</td>
        <td>${e.free_pages ?? ""}</td>
        <td>${e.evictions ?? ""}</td></tr>`).join("") ||
        '<tr><td class="dim" colspan="8">no engines warm</td></tr>'}
      </table></div>
    <div class="panel"><h2>resilience</h2>
      <table><tr><th>engine</th><th>crashes</th><th>stalls</th>
        <th>requeues</th><th>shed</th><th>timeouts</th>
        <th>retries</th></tr>
      ${Object.entries(hl.engines || {}).map(([name, e]) => `
        <tr><td>${esc(name)}</td>
        <td>${e.engine_crashes ?? 0}</td>
        <td>${e.stall_events ?? 0}</td>
        <td>${e.requeues ?? 0}</td>
        <td>${e.shed_turns ?? 0}</td>
        <td>${e.deadline_timeouts ?? 0}</td>
        <td>${e.fault_retries ?? 0}</td></tr>`).join("") ||
        '<tr><td class="dim" colspan="7">no engines warm</td></tr>'}
      </table>
      <h2 style="margin-top:.6rem">scheduler</h2>
      <table><tr><th>engine</th><th>class</th><th>queued</th>
        <th>ttft (target)</th><th>tpot (target)</th>
        <th>chunk budget</th><th>shed</th><th>rung</th></tr>
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.scheduler)
        .flatMap(([name, e]) =>
          Object.entries(e.scheduler.classes || {}).map(([cls, c]) => `
        <tr><td>${esc(name)}</td>
        <td>${esc(cls)}</td>
        <td>${c.queued ?? 0}</td>
        <td><span class="pill ${c.ttft_ok ? "verified" : "failed"}">${
          c.ttft_ema_s == null ? "—" : `${c.ttft_ema_s}s`}</span>
          <span class="dim">(${c.ttft_target_s}s)</span></td>
        <td><span class="pill ${c.tpot_ok ? "verified" : "failed"}">${
          c.tpot_ema_s == null ? "—" : `${c.tpot_ema_s}s`}</span>
          <span class="dim">(${c.tpot_target_s}s)</span></td>
        <td>${c.chunk_budget}/win
          <span class="dim">${Math.round(
            (c.chunk_budget_util || 0) * 100)}% used ·
            ${c.chunks_written ?? 0} chunks</span></td>
        <td>${c.shed ?? 0}</td>
        <td><span class="pill ${c.rung ? "pending" : "verified"}">${
          esc(DEGRADE_LABELS[c.rung] || c.rung)}</span></td>
        </tr>`)).join("") ||
        '<tr><td class="dim" colspan="8">no engines warm</td></tr>'}
      </table>
      <h2 style="margin-top:.6rem">speculation</h2>
      <table><tr><th>engine</th><th>class</th><th>γ live</th>
        <th>γ adapted</th><th>accept ema</th><th>acceptance</th>
        <th>proposed</th><th>accepted</th><th>state</th></tr>
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.spec && e.spec.gamma_max > 0)
        .flatMap(([name, e]) =>
          Object.entries(e.spec.classes || {}).map(([cls, s]) => `
        <tr><td>${esc(name)}
          <span class="dim">γmax ${e.spec.gamma_max}${
            e.spec.draft_model
              ? ` · draft ${esc(e.spec.draft_model)}` : ""}</span></td>
        <td>${esc(cls)}</td>
        <td>${s.gamma ?? 0}</td>
        <td>${s.gamma_adapted ?? 0}</td>
        <td>${s.accept_ema == null ? "—" : s.accept_ema.toFixed(2)}</td>
        <td>${s.acceptance == null ? "—" : s.acceptance.toFixed(2)}</td>
        <td>${s.proposed ?? 0}</td>
        <td>${s.accepted ?? 0}</td>
        <td><span class="pill ${s.off ? "pending" : "verified"}">${
          s.off ? `off (${s.throttles ?? 0} throttles)` : "drafting"}
          </span></td>
        </tr>`)).join("") ||
        '<tr><td class="dim" colspan="9">speculation disabled / no engines warm</td></tr>'}
      </table>
      <h2 style="margin-top:.6rem">fused window</h2>
      <table><tr><th>engine</th><th>mode</th><th>windows</th>
        <th>fused chunks</th><th>dp windows</th>
        <th>chunks / shard</th></tr>
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.fused_window_mode)
        .map(([name, e]) => `
        <tr><td>${esc(name)}${
          e.fused_window_disabled_reason
            ? `<span class="dim">${esc(e.fused_window_disabled_reason)}</span>`
            : ""}</td>
        <td><span class="pill ${
          e.fused_window_mode === "off" ? "pending" : "verified"}">${
          esc(e.fused_window_mode)}</span></td>
        <td>${e.fused_windows ?? 0}</td>
        <td>${e.fused_chunks ?? 0}</td>
        <td>${e.fused_dp ? e.fused_dp.windows ?? 0 : "—"}</td>
        <td>${e.fused_dp
          ? esc((e.fused_dp.chunks_per_shard || []).join(" / "))
          : "—"}</td>
        </tr>`).join("") ||
        '<tr><td class="dim" colspan="6">no engines warm</td></tr>'}
      </table>
      <h2 style="margin-top:.6rem">slo attribution</h2>
      <table><tr><th>class</th><th>turns</th><th>ttft mean</th>
        <th>slo misses</th><th>queue</th><th>prefill</th>
        <th>dispatch</th><th>drain</th><th>host</th>
        <th>offload</th></tr>
      ${Object.entries(hl.trace?.classes || {}).map(([cls, a]) => {
        const share = (ms) => a.wall_ms
          ? `${Math.round((ms / a.wall_ms) * 100)}%` : "—";
        const misses = (a.ttft_violations || 0) + (a.tpot_violations || 0);
        return `
        <tr><td>${esc(cls)}</td>
        <td>${a.turns ?? 0}
          <span class="dim">${a.errors ? `${a.errors} err` : ""}
            ${a.shed ? `${a.shed} shed` : ""}
            ${a.faulted ? `${a.faulted} faulted` : ""}</span></td>
        <td>${a.ttft_ms_mean == null ? "—"
          : `${a.ttft_ms_mean.toFixed(0)}ms`}</td>
        <td><span class="pill ${misses ? "failed" : "verified"}">${
          misses}</span></td>
        <td>${share(a.queue_ms)}</td>
        <td>${share(a.prefill_ms)}</td>
        <td>${share(a.dispatch_ms)}</td>
        <td>${share(a.drain_ms)}</td>
        <td>${share(a.decode_host_ms)}</td>
        <td>${share(a.offload_restore_ms)}</td></tr>`;
      }).join("") ||
        '<tr><td class="dim" colspan="10">no finished turns traced (ROOM_TPU_TRACE)</td></tr>'}
      </table>
      <h2 style="margin-top:.6rem">kv offload</h2>
      <table><tr><th>engine</th><th>host tier</th><th>disk tier</th>
        <th>out</th><th>in</th><th>prefetch</th><th>fallbacks</th>
        <th>restore latency</th></tr>
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.offload)
        .map(([name, e]) => `
        <tr><td>${esc(name)}</td>
        <td>${e.offload.host_entries} · ${mb(e.offload.host_bytes)}</td>
        <td>${e.offload.disk_entries} · ${mb(e.offload.disk_bytes)}</td>
        <td>${e.offloads ?? 0}</td>
        <td>${e.offload_restores ?? 0}</td>
        <td>${e.offload_prefetches ?? 0}</td>
        <td>${(e.offload_resident_fallbacks ?? 0) +
              (e.offload_reprefills ?? 0)}</td>
        <td class="dim">${esc(histStr(e.offload.restore_ms_hist))}</td>
        </tr>`).join("") ||
        '<tr><td class="dim" colspan="8">offload disabled / no engines warm</td></tr>'}
      </table>
      <h2 style="margin-top:.6rem">swarm runtime</h2>
      <div class="kv">
        <span class="k">agent loops alive</span>
          <span>${hl.swarm?.loops_alive ?? 0}</span>
        <span class="k">loop restarts</span>
          <span>${hl.swarm?.restarts ?? 0}
            ${hl.swarm?.hang_replacements
              ? `<span class="dim">(${hl.swarm.hang_replacements} hung)</span>`
              : ""}</span>
        <span class="k">loop crashes</span>
          <span>${hl.swarm?.crashes ?? 0}</span>
        <span class="k">unhealthy workers</span>
          <span>${Object.keys(hl.swarm?.unhealthy_workers || {}).length
            ? `<span class="pill failed">${
                Object.keys(hl.swarm.unhealthy_workers).map((w) =>
                  `#${esc(w)}`).join(" ")}</span>`
            : '<span class="pill verified">none</span>'}</span>
        <span class="k">journal backlog</span>
          <span>${hl.swarm?.journal?.backlog ?? 0}</span>
        <span class="k">recovered after crash</span>
          <span>${hl.swarm?.journal?.recovered ?? 0}
            <span class="dim">effects replay-skipped:
              ${hl.swarm?.journal?.replay_consumed ?? 0}</span></span>
      </div>
      ${(hl.swarm?.shards?.n_shards ?? 1) > 1 ? `
      <h2 style="margin-top:.6rem">swarm shards
        <span class="dim">epoch ${hl.swarm.shards.placement?.epoch ?? 0}
          · ${hl.swarm.shards.cross_shard_messages ?? 0} x-shard msgs
          · ${hl.swarm.shards.dedup_skips ?? 0} deduped
          · ${hl.swarm.shards.adoptions ?? 0} adoptions</span></h2>
      <table><tr><th>shard</th><th>state</th><th>rooms</th>
        <th>events</th><th>msgs in/out</th><th>escalations</th>
        <th>journal backlog</th><th>adopted</th></tr>
      ${(hl.swarm.shards.shards || []).map((s) => `
        <tr><td>${s.shard}</td>
        <td><span class="pill ${
          s.state === "serving" ? "verified"
          : s.state === "dead" ? "failed" : "pending"
        }">${esc(s.state)}</span></td>
        <td>${s.rooms_created ?? 0}</td>
        <td>${s.events ?? 0}</td>
        <td>${s.messages_in ?? 0} / ${s.messages_out ?? 0}</td>
        <td>${s.escalations ?? 0}</td>
        <td class="dim">${s.journal?.backlog ?? 0}</td>
        <td class="dim">${(s.adopted || []).map((a) =>
          `#${a}`).join(" ") || "—"}</td>
        </tr>`).join("")}
      </table>` : ""}
      ${hl.swarm?.proc ? `
      <h2 style="margin-top:.6rem">swarm shard processes
        <span class="dim">epoch ${hl.swarm.proc.placement?.epoch ?? 0}
          · ${hl.swarm.proc.dispatches ?? 0} dispatches
          · ${hl.swarm.proc.dedup_skips ?? 0} deduped
          · ${hl.swarm.proc.restarts ?? 0} restarts
          · ${hl.swarm.proc.adoptions ?? 0} adoptions
          · ${hl.swarm.proc.orphans_reaped ?? 0} orphans reaped</span></h2>
      <table><tr><th>shard</th><th>state</th><th>pid</th>
        <th>restarts/window</th><th>msgs in/out</th>
        <th>escalations</th><th>journal backlog</th>
        <th>journal bytes</th><th>adopted</th></tr>
      ${(hl.swarm.proc.children || []).map((c) => `
        <tr><td>${c.shard}</td>
        <td><span class="pill ${
          c.state === "serving" ? "verified"
          : (c.state === "dead" || c.state === "failed") ? "failed"
          : "pending"
        }">${esc(c.state)}</span>${
          c.adopter != null
            ? ` <span class="dim">→ #${c.adopter}</span>` : ""
        }</td>
        <td class="dim">${c.pid ?? "—"}</td>
        <td>${c.restarts_in_window ?? 0}/${
          hl.swarm.proc.restart_budget ?? 0}</td>
        <td>${c.messages_in ?? 0} / ${c.messages_out ?? 0}</td>
        <td>${c.escalations ?? 0}</td>
        <td class="dim">${c.journal?.backlog ?? 0}</td>
        <td class="dim">${c.journal_bytes ?? 0}</td>
        <td class="dim">${(c.adopted || []).map((a) =>
          `#${a}`).join(" ") || "—"}</td>
        </tr>`).join("")}
      </table>
      ${hl.swarm.proc.slo?.classes ? `
      <div class="kv">
        <span class="k">fleet SLO (all processes)</span>
          <span>${Object.entries(hl.swarm.proc.slo.classes).map(
            ([cls, a]) =>
              `${esc(cls)}: ${a.turns ?? 0} turns` +
              (a.ttft_ms_mean != null
                ? ` · ttft ${a.ttft_ms_mean}ms` : "") +
              ` · ${(a.ttft_violations ?? 0) +
                    (a.tpot_violations ?? 0)} SLO misses`
          ).join("<br>")}</span>
      </div>` : ""}` : ""}
      <h2 style="margin-top:.6rem">lifecycle</h2>
      <div class="kv">
        <span class="k">process phase</span>
          <span><span class="pill ${
            hl.lifecycle?.phase === "serving" ? "verified"
            : hl.lifecycle?.phase === "draining" ? "failed" : "pending"
          }">${esc(hl.lifecycle?.phase || "unknown")}</span></span>
        <span class="k">last shutdown</span>
          <span>${hl.lifecycle?.last_shutdown === "crash"
            ? '<span class="pill failed">crash</span>'
            : hl.lifecycle?.last_shutdown === "clean"
              ? '<span class="pill verified">clean</span>'
              : esc(hl.lifecycle?.last_shutdown || "—")}</span>
        ${hl.lifecycle?.drain_ms != null
          ? `<span class="k">last drain</span>
             <span>${hl.lifecycle.drain_ms}ms</span>`
          : ""}
      </div>
      <table><tr><th>engine</th><th>phase</th><th>resumed</th>
        <th>re-prefilled</th><th>spooled</th><th>abandoned</th>
        <th>drain</th></tr>
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.lifecycle)
        .map(([name, e]) => `
        <tr><td>${esc(name)}</td>
        <td>${esc(e.lifecycle.phase || "")}</td>
        <td>${e.lifecycle.sessions_resumed ?? 0}</td>
        <td>${e.lifecycle.sessions_reprefill ?? 0}</td>
        <td>${e.lifecycle.sessions_spooled ?? 0}</td>
        <td>${e.lifecycle.sessions_abandoned ?? 0}</td>
        <td class="dim">${e.lifecycle.drain_ms
          ? `${e.lifecycle.drain_ms}ms` : "—"}</td></tr>`).join("") ||
        '<tr><td class="dim" colspan="7">no engines warm</td></tr>'}
      </table>
      ${Object.entries(hl.engines || {}).some(([n, e]) => e.fleet) ? `
      <h2 style="margin-top:.6rem">fleet</h2>
      <table><tr><th>model</th><th>replica</th><th>role</th>
        <th>state</th><th>score</th><th>strikes</th><th>placed</th>
        <th>failovers</th><th>re-homed</th><th>drains</th></tr>
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.fleet)
        .flatMap(([name, e]) =>
          Object.entries(e.fleet.health || {}).map(([rid, r]) => `
        <tr><td>${esc(name)}</td>
        <td>${esc(rid)}</td>
        <td class="dim">${esc(r.role || "mixed")}</td>
        <td><span class="pill ${
          r.state === "serving" && r.healthy ? "verified"
          : r.state === "dead" ? "failed" : "pending"
        }">${esc(r.state)}</span></td>
        <td>${r.score ?? ""}</td>
        <td>${r.strikes ?? 0}</td>
        <td>${e.fleet.placements?.[rid] ?? 0}</td>
        <td>${e.fleet.failovers ?? 0}</td>
        <td>${e.fleet.sessions_rehomed ?? 0}
          <span class="dim">(${e.fleet.sessions_rehomed_warm ?? 0}
            warm)</span></td>
        <td>${e.fleet.bluegreen_drains ?? 0}</td>
        </tr>`)).join("")}
      </table>
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.fleet?.disagg?.enabled)
        .map(([name, e]) => `
      <div class="kv" style="margin-top:.4rem">
        <span class="k">disagg ships (${esc(name)})</span>
          <span>${e.fleet.disagg.ships ?? 0}
            <span class="dim">(${e.fleet.disagg.ships_warm ?? 0} warm,
              ${e.fleet.disagg.ships_reprefill ?? 0} re-prefill,
              ${e.fleet.disagg.wire_errors ?? 0} wire errors)</span>
          </span>
        <span class="k">mirror</span>
          <span>${e.fleet.mirror?.tokens ?? 0} tokens
            <span class="dim">(cap ${e.fleet.mirror?.cap_tokens ?? 0},
              ${e.fleet.mirror?.evictions ?? 0} evictions${
              e.fleet.mirror?.journal
                ? `, journal ${e.fleet.mirror.journal.appends ?? 0}
                   appends / ${e.fleet.mirror.journal.errors ?? 0}
                   errors` : ""})</span>
          </span>
      </div>`).join("")}
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) =>
          (e.fleet?.router_shards?.count ?? 1) > 1)
        .map(([name, e]) => `
      <h2 style="margin-top:.6rem">router shards (${esc(name)})</h2>
      <table><tr><th>shard</th><th>state</th><th>rooms</th>
        <th>journal</th><th>adoptions</th></tr>
      ${Object.entries(e.fleet.router_shards.shards || {})
        .map(([sk, s]) => `
        <tr><td>${esc(sk)}</td>
        <td><span class="pill ${
          s.state === "serving" ? "verified"
          : s.state === "dead" ? "failed" : "pending"
        }">${esc(s.state)}</span></td>
        <td>${s.rooms ?? 0}</td>
        <td class="dim">${s.journal_bytes ?? 0} B</td>
        <td>${s.adoptions ?? 0}</td></tr>`).join("")}
      </table>
      <div class="kv" style="margin-top:.2rem">
        <span class="k">placement</span>
          <span>epoch ${e.fleet.router_shards.epoch ?? 0}
            <span class="dim">(${e.fleet.router_shards.crashes ?? 0}
              shard crashes,
              ${e.fleet.router_shards.sessions_adopted ?? 0} sessions
              adopted,
              ${e.fleet.router_shards.placement_refusals ?? 0}
              stale-epoch refusals)</span></span>
      </div>`).join("")}
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.fleet?.pod?.enabled)
        .map(([name, e]) => `
      <div class="kv" style="margin-top:.4rem">
        <span class="k">pod members (${esc(name)})</span>
          <span>${Object.entries(e.fleet.pod.members || {})
            .map(([mid, m]) => `<span class="pill ${
              m.state === "alive" ? "verified"
              : m.state === "dead" ? "failed" : "pending"
            }">${esc(mid)}: ${esc(m.state)}</span>`).join(" ")}
            <span class="dim">(${e.fleet.pod.heartbeats_sent ?? 0}
              beats, ${e.fleet.pod.heartbeats_lost ?? 0} lost,
              ${e.fleet.pod.lease_rehomes ?? 0} lease re-homes,
              ${e.fleet.fence_refusals ?? 0} fence refusals)</span>
          </span>
      </div>`).join("")}` : ""}
      ${Object.entries(hl.engines || {}).some(
        ([n, e]) => e.prefix_store) ? `
      <h2 style="margin-top:.6rem">prefix store</h2>
      <table><tr><th>engine</th><th>entries</th><th>hits</th>
        <th>misses</th><th>publishes</th><th>evictions</th>
        <th>pulled</th><th>errors</th></tr>
      ${Object.entries(hl.engines || {})
        .filter(([name, e]) => e.prefix_store)
        .map(([name, e]) => `
        <tr><td>${esc(name)}</td>
        <td>${e.prefix_store.entries ?? 0}</td>
        <td>${e.prefix_store.hits ?? 0}</td>
        <td>${e.prefix_store.misses ?? 0}</td>
        <td>${e.prefix_store.publishes ?? 0}</td>
        <td>${e.prefix_store.evictions ?? 0}</td>
        <td class="dim">${Math.round(
          (e.prefix_store.bytes_pulled ?? 0) / 1024)}KB</td>
        <td>${(e.prefix_store.pull_errors ?? 0) +
          (e.prefix_store.publish_errors ?? 0)}</td></tr>`).join("")}
      </table>` : ""}
      ${Object.keys(hl.faults || {}).length
        ? `<div class="dim" style="margin-top:.4rem">armed faults: ${
            Object.entries(hl.faults).map(([n, f]) =>
              `${esc(n)} (fired ${f.fired})`).join(", ")}</div>`
        : ""}
      ${hl.invariants
        ? `<div class="${hl.invariants.violations ? "" : "dim"}"
             style="margin-top:.4rem">invariant witness: ${
            hl.invariants.violations
              ? `<span class="pill pending">${hl.invariants.violations
                } violation(s)</span> ${
                Object.entries(hl.invariants.by_invariant || {})
                  .map(([n, c]) => `${esc(n)}×${c}`).join(", ")}`
              : `armed, clean (${hl.invariants.probes ?? 0} probes)`
          }</div>`
        : ""}
      ${(hl.fallback_models || []).length
        ? `<div class="dim">fallback chain: ${
            esc((hl.fallback_models || []).join(" → "))}</div>`
        : ""}</div>
    <div class="panel"><h2>model status</h2>
      <table>${Object.entries(models.data || {}).map(([name, m]) => `
        <tr><td>${esc(name)}</td>
        <td>${m.ready
          ? '<span class="pill verified">ready</span>'
          : '<span class="pill pending">cold</span>'}</td>
        <td class="dim">${esc(m.detail || "")}</td>
        <td><button class="ghost" onclick="provision('${esc(name)}')">
          load weights</button></td></tr>`).join("")}</table>
      <div class="log hidden" id="provisionLog"
           style="margin-top:.5rem"></div></div>`;
  subscribe("tpu-model");
}

async function provision(model) {
  const out = await api("POST", "/api/tpu/provision", {model});
  if (!out.data) return;
  $("provisionLog").classList.remove("hidden");
  $("provisionLog").innerHTML =
    `<div class="t">provision session ${esc(out.data.session)}</div>`;
  const sid = out.data.session;
  const poll = async () => {
    const v = (await api("GET", `/api/tpu/provision/${sid}`)).data;
    if (v && v.status === "running") setTimeout(poll, 1500);
    else refreshView();
  };
  poll();
}

// ---- status (reference: StatusPanel.tsx — version, update
// diagnostics, runtime + usage at a glance) ----

async function renderStatus(el) {
  const [st, upd, queens, rooms] = await Promise.all([
    api("GET", "/api/status"),
    api("GET", "/api/update"),
    api("GET", "/api/rooms/queen-states"),
    api("GET", "/api/rooms"),
  ]);
  const s = st.data || {};
  const u = upd.data || {};
  const usage = await Promise.all((rooms.data || []).map(async r => ({
    room: r,
    u: (await api("GET", `/api/rooms/${r.id}/usage`)).data || {},
  })));
  el.innerHTML = `
    <div class="cols"><div>
    <div class="panel"><h2>server</h2>
      <div class="kv">
        <span class="k">version</span><span>${esc(s.version)}</span>
        <span class="k">platform</span>
          <span>${esc(s.platform)} × ${esc(s.devices)}</span>
        <span class="k">rooms</span>
          <span>${esc(s.activeRooms)} active / ${esc(s.rooms)}</span>
        <span class="k">uptime</span>
          <span>${Math.round(s.uptime_s || 0)}s</span>
      </div></div>
    <div class="panel"><h2>update</h2>
      <div class="kv">
        <span class="k">current</span>
          <span>${esc(u.currentVersion)}</span>
        <span class="k">latest</span>
          <span>${esc(u.updateInfo?.latestVersion || "unknown")}
          ${u.updateInfo?.updateAvailable
            ? '<span class="pill pending">update available</span>'
            : ""}</span>
        <span class="k">auto-update</span>
          <span>${esc(u.autoUpdate?.state || "idle")}</span>
        <span class="k">last check</span>
          <span>${when(u.diagnostics?.lastCheckAt) || "never"}</span>
        <span class="k">diagnostics</span>
          <span class="dim">${esc(u.diagnostics?.lastErrorMessage ||
            "ok")}</span>
      </div>
      <div class="row">
        <button class="ghost" onclick="statusCheckUpdate()">
          check now</button>
      </div></div>
    </div><div>
    <div class="panel"><h2>queens</h2>
      <table><tr><th>room</th><th>queen</th><th>state</th></tr>
      ${Object.entries(queens.data || {}).map(([roomId, q]) => `
        <tr><td>#${esc(roomId)} ${esc((rooms.data || []).find(r =>
          r.id === Number(roomId))?.name || "")}</td>
        <td>#${esc(q.queenWorkerId)}</td>
        <td><span class="pill">${esc(q.state || "idle")}</span></td>
        </tr>`).join("") ||
        '<tr><td class="dim" colspan="3">no rooms</td></tr>'}</table>
    </div>
    <div class="panel"><h2>token usage</h2>
      <table><tr><th>room</th><th>cycles</th><th>in</th><th>out</th></tr>
      ${usage.map(x => `
        <tr><td>${esc(x.room.name)}</td><td>${x.u.cycles ?? 0}</td>
        <td>${x.u.input_tokens ?? 0}</td>
        <td>${x.u.output_tokens ?? 0}</td></tr>`).join("") ||
        '<tr><td class="dim" colspan="4">no usage yet</td></tr>'}</table>
    </div></div></div>`;
}

async function statusCheckUpdate() {
  await api("POST", "/api/update/check", {});
  refreshView();
}

// ---- goals (all-rooms tree browser; reference: GoalsPanel.tsx) ----

async function renderGoals(el) {
  const rooms = (await api("GET", "/api/rooms")).data || [];
  const blocks = await Promise.all(rooms.map(async r => {
    const goals = (await api("GET", `/api/rooms/${r.id}/goals`)).data
      || [];
    const row = (g, depth) =>
      `<tr><td style="padding-left:${depth * 14 + 4}px">
        #${g.id} ${esc(g.description)}</td>
      <td>${Math.round((g.progress || 0) * 100)}%</td>
      <td><span class="pill ${esc(g.status)}">${esc(g.status)}</span></td>
      <td style="white-space:nowrap">
        <button class="ghost" onclick="goalAction(${g.id},'complete')">
          done</button>
        <button class="ghost" onclick="goalAction(${g.id},'abandon')">
          drop</button>
        <button class="ghost" onclick="goalNote(${g.id})">note</button>
      </td></tr>` +
      (g.children || []).map(c => row(c, depth + 1)).join("");
    return `<div class="panel"><h2>${esc(r.name)}</h2>
      <table>${goals.map(g => row(g, 0)).join("") ||
        '<tr><td class="dim">no goals</td></tr>'}</table>
      <div class="row">
        <input id="goalAdd-${r.id}" placeholder="add a goal…">
        <button class="ghost" onclick="goalAddTo(${r.id})">add</button>
      </div></div>`;
  }));
  el.innerHTML = blocks.join("") ||
    '<div class="panel"><div class="dim">no rooms yet</div></div>';
}

async function goalAddTo(roomId) {
  const input = $(`goalAdd-${roomId}`);
  if (!input.value.trim()) return;
  await api("POST", `/api/rooms/${roomId}/goals`,
    {description: input.value.trim()});
  refreshView();
}

async function goalNote(goalId) {
  const update = await promptDialog(
    "progress note for goal #" + goalId);
  if (!update) return;
  await api("POST", `/api/goals/${goalId}/updates`, {update});
  refreshView();
}

// ---- messages (inter-room mail; reference: MessagesPanel.tsx) ----

let msgRoom = null;

async function renderMessages(el) {
  const rooms = (await api("GET", "/api/rooms")).data || [];
  if (msgRoom === null && rooms.length) msgRoom = rooms[0].id;
  el.innerHTML = `
    <div class="panel"><h2>room messages</h2>
      <div class="row">
        <select id="msgRoomSel">
          ${rooms.map(r => `<option value="${r.id}"
            ${r.id === msgRoom ? "selected" : ""}>
            ${esc(r.name)}</option>`).join("")}
        </select>
        <button class="ghost" onclick="msgPick()">open</button>
        <button class="ghost" onclick="msgReadAll()">mark all read
        </button>
      </div>
      <table id="msgTable"></table>
      <h2 style="margin-top:.8rem">send</h2>
      <div class="row">
        <select id="msgTo">${rooms.map(r =>
          `<option value="${r.id}">${esc(r.name)}</option>`).join("")}
        </select>
        <input id="msgSubject" placeholder="subject">
        <input id="msgBody" placeholder="message…">
        <button class="act" onclick="msgSend()">send</button>
      </div></div>`;
  if (msgRoom !== null) loadMessages();
}

async function loadMessages() {
  const out = await api("GET", `/api/rooms/${msgRoom}/messages`);
  const tbl = $("msgTable");
  if (!tbl) return;
  tbl.innerHTML =
    "<tr><th>from</th><th>subject</th><th>body</th><th></th></tr>" +
    ((out.data || []).map(m => `
      <tr class="${m.read_at ? "dim" : ""}">
      <td>#${esc(m.from_room_id ?? "?")}</td>
      <td>${esc(m.subject || "")}</td>
      <td>${esc(String(m.body || "").slice(0, 140))}</td>
      <td style="white-space:nowrap">
        ${m.read_at ? "" : `<button class="ghost"
          onclick="msgRead(${m.id})">read</button>`}
        <button class="ghost" onclick="msgReply(${m.id})">reply</button>
      </td></tr>`).join("") ||
      '<tr><td class="dim" colspan="4">no messages</td></tr>');
}

function msgPick() {
  const v = parseInt($("msgRoomSel").value, 10);
  if (isNaN(v)) return;   // empty select: nothing to open
  msgRoom = v;
  loadMessages();
}

async function msgSend() {
  const body = $("msgBody").value.trim();
  if (!body || msgRoom === null) return;
  await api("POST", `/api/rooms/${msgRoom}/messages`, {
    toRoomId: parseInt($("msgTo").value, 10),
    subject: $("msgSubject").value.trim(),
    body,
  });
  $("msgBody").value = "";
  loadMessages();
}

async function msgRead(id) {
  await api("POST", `/api/messages/${id}/read`, {});
  loadMessages();
}

async function msgReadAll() {
  if (msgRoom === null) return;
  await api("POST", `/api/rooms/${msgRoom}/messages/read-all`, {});
  loadMessages();
}

async function msgReply(id) {
  const body = await promptDialog("reply to message #" + id);
  if (!body) return;
  await api("POST", `/api/messages/${id}/reply`, {body});
  loadMessages();
}

// ---- transactions (reference: TransactionsPanel.tsx) ----

async function renderTransactions(el) {
  const rooms = (await api("GET", "/api/rooms")).data || [];
  const blocks = await Promise.all(rooms.map(async r => {
    const [bal, txs] = await Promise.all([
      api("GET", `/api/rooms/${r.id}/wallet/balance`),
      api("GET", `/api/rooms/${r.id}/wallet/transactions`),
    ]);
    const b = bal.data || {};
    return `<div class="panel"><h2>${esc(r.name)}
        <span class="dim" style="font-weight:normal;font-size:.8em">
        ${esc(b.address || "")}</span></h2>
      <div class="dim">${Object.entries(b.balances || {}).map(
        ([chain, v]) => `${esc(chain)}: ${esc(JSON.stringify(v))}`
      ).join(" · ") || "balances unavailable offline"}</div>
      <table><tr><th>when</th><th>type</th><th>category</th>
        <th>amount</th><th>counterparty</th><th>status</th></tr>
      ${((txs.data || [])).map(t => `
        <tr><td class="dim">${when(t.created_at)}</td>
        <td>${esc(t.type)}</td>
        <td>${esc(t.category || "")}</td><td>${esc(t.amount)}</td>
        <td class="dim">
          ${esc(String(t.counterparty || "").slice(0, 14))}</td>
        <td><span class="pill ${esc(t.status)}">${esc(t.status)}</span>
          ${t.tx_hash ? `<span class="dim">
            ${esc(String(t.tx_hash).slice(0, 12))}…</span>` : ""}
        </td></tr>`).join("") ||
        '<tr><td class="dim" colspan="6">no transactions</td></tr>'}
      </table></div>`;
  }));
  el.innerHTML = blocks.join("") ||
    '<div class="panel"><div class="dim">no rooms yet</div></div>';
}

// ---- runs (task run history; reference: routes/runs.ts + ui) ----

async function renderRuns(el) {
  const runs = (await api("GET", "/api/runs")).data || [];
  el.innerHTML = `
    <div class="cols"><div class="panel"><h2>task runs</h2>
      <table><tr><th>run</th><th>task</th><th>status</th>
        <th>started</th><th></th></tr>
      ${runs.map(r => `
        <tr><td>#${r.id}</td><td>${esc(r.task_name || r.task_id)}</td>
        <td><span class="pill ${esc(r.status)}">${esc(r.status)}</span>
        </td>
        <td class="dim">${when(r.started_at)}</td>
        <td><button class="ghost" onclick="runLogs(${r.id})">logs
        </button></td></tr>`).join("") ||
        '<tr><td class="dim" colspan="5">no runs yet</td></tr>'}
      </table></div>
    <div class="panel"><h2>run logs</h2>
      <div class="log" id="runLog">
        <span class="dim">pick a run</span></div></div></div>`;
}

async function runLogs(id) {
  const [run, logs] = await Promise.all([
    api("GET", `/api/runs/${id}`),
    api("GET", `/api/runs/${id}/logs`),
  ]);
  const r = run.data || {};
  $("runLog").innerHTML =
    `<div class="t">run #${id} · ${esc(r.status)} ·
      ${esc(String(r.result || "").slice(0, 200))}</div>` +
    ((logs.data || []).map(l =>
      `<div><span class="t">${esc(l.entry_type || l.level)}</span>
       ${esc(String(l.content || l.message || "").slice(0, 300))}</div>`
    ).join("") || '<div class="dim">no log entries</div>');
}

// ---- feed (public activity; reference: public-feed.ts + cloud UI) ----

async function renderFeed(el) {
  const out = await api("GET", "/api/feed");
  el.innerHTML = `<div class="panel"><h2>public feed</h2>
    <div class="log">${((out.data || [])).map(a => `
      <div><span class="t">${when(a.created_at)}</span>
        <b>${esc(a.room_name || a.room_id || "")}</b>
        ${esc(a.event_type || "")}:
        ${esc(String(a.summary || "").slice(0, 240))}
      </div>`).join("") ||
      '<div class="dim">nothing public yet</div>'}</div></div>`;
}

// ---- setup (guided room creation; reference:
// RoomSetupGuideModal.tsx) ----

async function renderSetup(el) {
  const [models, providers, templates] = await Promise.all([
    api("GET", "/api/models/status"),
    api("GET", "/api/providers"),
    api("GET", "/api/templates"),
  ]);
  const ms = models.data || {};
  const tpuReady = Object.values(ms).some(m => m.ready);
  el.innerHTML = `
    <div class="panel"><h2>set up a room</h2>
      <div class="dim">three steps: pick a compute backend, pick a
        template, name the room. The queen starts herself.</div>
      <h2 style="margin-top:.8rem">1 · compute</h2>
      <table><tr><th>backend</th><th>status</th><th></th></tr>
        <tr><td>tpu (in-tree serving)</td>
          <td>${tpuReady
            ? '<span class="pill verified">ready</span>'
            : '<span class="pill pending">weights not loaded</span>'}
          </td>
          <td class="dim">load weights in the tpu panel</td></tr>
        ${Object.entries(providers.data || {}).map(([key, p]) => `
          <tr><td>${esc(key)} cli</td>
          <td>${p.connected
            ? '<span class="pill verified">ready</span>'
            : p.installed
              ? '<span class="pill pending">not logged in</span>'
              : '<span class="pill pending">not installed</span>'}</td>
          <td class="dim">${esc(p.version || "")}</td></tr>`).join("")}
      </table>
      <h2 style="margin-top:.8rem">2 · template</h2>
      <div class="row">
        <select id="setupTemplate">
          <option value="">blank room</option>
          ${((templates.data || {}).rooms || []).map(t =>
            `<option value="${esc(t.key)}">${esc(t.name)} —
             ${esc(t.description || "")}</option>`).join("")}
        </select>
        <select id="setupModel">
          <option value="tpu">tpu</option>
          <option value="echo">echo (test)</option>
          ${Object.entries(providers.data || {}).filter(([, p]) =>
            p.connected).map(([key]) =>
            `<option value="${esc(key)}">${esc(key)}</option>`
          ).join("")}
        </select>
      </div>
      <h2 style="margin-top:.8rem">3 · name + create</h2>
      <div class="row">
        <input id="setupName" placeholder="room name…">
        <button class="act" onclick="setupCreate()">create room</button>
      </div>
      <div class="dim" id="setupResult"></div></div>`;
}

async function setupCreate() {
  const name = $("setupName").value.trim();
  const template = $("setupTemplate").value;
  const model = $("setupModel").value;
  let out;
  if (template) {
    out = await api("POST", "/api/templates/instantiate",
      {template, name: name || undefined, workerModel: model});
  } else {
    if (!name) return;
    out = await api("POST", "/api/rooms",
      {name, workerModel: model});
  }
  if (out.data?.id) {
    $("setupResult").textContent =
      `room #${out.data.id} created — open the rooms panel to start it`;
  }
}

// ---- usage (token accounting; reference: routes/rooms.ts usage +
// clerk_usage table driving the ref UI's usage readouts) ----

async function renderUsage(el) {
  const rooms = (await api("GET", "/api/rooms")).data || [];
  const usages = await Promise.all(rooms.map(async r => ({
    room: r,
    u: (await api("GET", `/api/rooms/${r.id}/usage`)).data || {},
  })));
  const maxTok = Math.max(1, ...usages.map(x =>
    (x.u.input_tokens || 0) + (x.u.output_tokens || 0)));
  const clerkRows = (await api("GET", "/api/clerk/usage")).data || [];
  const clerkTok = clerkRows.reduce((a, c) =>
    a + (c.input_tokens || 0) + (c.output_tokens || 0), 0);
  el.innerHTML = `<div class="panel"><h2>token usage by room</h2>
    <table><tr><th>room</th><th>cycles</th><th>in</th><th>out</th>
      <th style="width:40%"></th></tr>
    ${usages.map(({room, u}) => {
      const tot = (u.input_tokens || 0) + (u.output_tokens || 0);
      return `<tr><td>${esc(room.name)}</td>
        <td>${u.cycles || 0}</td>
        <td>${(u.input_tokens || 0).toLocaleString()}</td>
        <td>${(u.output_tokens || 0).toLocaleString()}</td>
        <td><div class="bar" style="width:${
          Math.round(100 * tot / maxTok)}%"></div></td></tr>`;
    }).join("")}</table></div>
    <div class="panel"><h2>clerk usage</h2>
    <div class="dim">${clerkRows.length} turns ·
      ${clerkTok.toLocaleString()} tokens</div>
    <table><tr><th>when</th><th>model</th><th>in</th><th>out</th></tr>
    ${clerkRows.slice(0, 25).map(c => `
      <tr><td>${esc(when(c.created_at))}</td><td>${esc(c.model || "")}</td>
      <td>${c.input_tokens || 0}</td><td>${c.output_tokens || 0}</td>
      </tr>`).join("")}</table></div>`;
}

// ---- providers (status, login + install sessions; reference:
// provider-auth.ts / provider-install.ts session UX) ----

let provPollTimer = null;

async function renderProviders(el) {
  const provs = (await api("GET", "/api/providers")).data || {};
  el.innerHTML = `<div class="panel"><h2>model providers</h2>
    <table><tr><th>provider</th><th>installed</th><th>connected</th>
      <th></th></tr>
    ${Object.entries(provs).map(([name, p]) => `<tr>
      <td><b>${esc(name)}</b>
        <div class="dim" style="font-size:.82em">
          ${esc(p.version || "")}</div></td>
      <td><span class="pill ${p.installed ? "ok" : ""}">
        ${p.installed ? "yes" : "no"}</span></td>
      <td><span class="pill ${p.connected ? "ok" : ""}">
        ${p.connected ? "yes" : "no"}</span></td>
      <td class="row" style="margin:0">
        <button class="ghost"
          onclick="provAuthStart('${esc(name)}')">login</button>
        <button class="ghost"
          onclick="provInstallStart('${esc(name)}')">install</button>
      </td></tr>`).join("")}</table>
    <div id="provSession"></div></div>`;
  if (provActive) provPollSession(provActive.action, provActive.sid);
}

let provActive = null;

async function provAuthStart(provider) {
  const out = await api("POST", `/api/providers/${provider}/auth/start`);
  if (out.data) {
    provPollSession("auth", out.data.sessionId);
  }
}

async function provInstallStart(provider) {
  const out = await api("POST",
    `/api/providers/${provider}/install/start`);
  if (out.data) {
    provPollSession("install", out.data.sessionId);
  }
}

async function provPollSession(action, sid) {
  if (!sid) return;
  clearTimeout(provPollTimer);
  const out = await api("GET", `/api/providers/${action}/sessions/${sid}`);
  const s = out.data;
  const box = $("provSession");
  if (!s || !box) return;           // session gone or panel left
  provActive = s.active ? {action, sid} : null;
  box.innerHTML = `
    <h2 style="margin-top:.8rem">${esc(s.provider)} ${action} session
      <span class="pill ${s.status === "completed" ? "ok" : ""}">
        ${esc(s.status)}</span></h2>
    ${s.verificationUrl ? `<div>open
      <a href="${esc(s.verificationUrl)}" target="_blank">
        ${esc(s.verificationUrl)}</a>
      ${s.deviceCode ? `and enter <b>${esc(s.deviceCode)}</b>` : ""}
      </div>` : ""}
    <pre class="log">${esc((s.lines || []).slice(-30)
      .map(l => l.text ?? l).join("\n"))}</pre>
    ${s.active ? `<button class="ghost"
      onclick="provCancelSession('${action}','${esc(sid)}')">cancel
      </button>` : ""}`;
  if (s.active) {
    provPollTimer = setTimeout(() => provPollSession(action, sid), 1500);
  }
}

async function provCancelSession(action, sid) {
  await api("POST", `/api/providers/${action}/sessions/${sid}/cancel`);
  provPollSession(action, sid);
}

// ---- memory graph + stats (reference: MemoryPanel + memory routes) ----

let memTab = "search";

function memShowTab(tab) {
  memTab = tab;
  refreshView();
}

async function renderMemoryGraph(container) {
  const stats = (await api("GET", "/api/memory/stats")).data || {};
  const ents = (await api("GET", "/api/memory/entities?limit=50"))
    .data || [];
  container.innerHTML = `
    <div class="dim" style="margin:.4rem 0">
      ${stats.entities || 0} entities · ${stats.observations || 0}
      observations · ${stats.relations || 0} relations ·
      ${stats.embedded || 0} embedded</div>
    <table>${ents.map(e => `
      <tr><td><b>${esc(e.name)}</b>
        <span class="dim">${esc(e.entity_type || "")}</span>
        <div id="entObs-${e.id}"></div></td>
      <td style="width:8rem" class="row">
        <button class="ghost" onclick="entObservations(${e.id})">
          observations</button>
      </td></tr>`).join("")}</table>
    <div class="row">
      <input id="relFrom" placeholder="from entity id…" style="width:8rem">
      <input id="relType" placeholder="type…" style="width:6rem">
      <input id="relTo" placeholder="to entity id…" style="width:8rem">
      <button class="ghost" onclick="relAdd()">relate</button>
    </div>`;
}

async function entObservations(id) {
  const out = await api("GET",
    `/api/memory/entities/${id}/observations`);
  const rows = out.data || [];
  $(`entObs-${id}`).innerHTML = `
    <ul style="margin:.3rem 0 .2rem 1rem;padding:0">
      ${rows.map(o => `<li style="font-size:.85em">${esc(o.content)}
        <a href="#" onclick="obsDelete(${o.id},${id});return false"
          class="dim">×</a></li>`).join("")}</ul>
    <div class="row" style="margin:.2rem 0 0">
      <input id="obsNew-${id}" placeholder="add observation…"
        style="font-size:.85em">
      <button class="ghost" onclick="obsAdd(${id})">+</button></div>`;
}

async function obsAdd(entityId) {
  const v = $(`obsNew-${entityId}`).value.trim();
  if (!v) return;
  await api("POST", `/api/memory/entities/${entityId}/observations`,
    {content: v});
  entObservations(entityId);
}

async function obsDelete(obsId, entityId) {
  await api("DELETE", `/api/memory/observations/${obsId}`);
  entObservations(entityId);
}

async function relAdd() {
  const fromId = parseInt($("relFrom").value.trim(), 10);
  const type = $("relType").value.trim() || "relates_to";
  const toId = parseInt($("relTo").value.trim(), 10);
  if (!fromId || !toId) return;
  await api("POST", "/api/memory/relations",
    {fromId, toId, relationType: type});
  refreshView();
}

// ---- help + guided walkthrough (reference: HelpPanel.tsx,
// RoomSetupGuideModal.tsx / ClerkSetupGuide.tsx step flows) ----

const TOUR_STEPS = [
  {view: "setup", text: "Welcome! This wizard creates your first " +
    "room: a queen plus a worker team with a shared goal. Pick a " +
    "template or describe the mission."},
  {view: "providers", text: "Connect a model provider. Local TPU " +
    "serving needs no login; claude:/codex: drive the CLIs; API " +
    "providers take a key."},
  {view: "tpu", text: "Provision the TPU model host here — the " +
    "hardware gate checks devices, HBM fit (with an int8 fallback " +
    "plan) and weights before loading."},
  {view: "rooms", text: "Start the room. The runtime loop wakes " +
    "workers on a cadence; quorum votes gate irreversible actions."},
  {view: "swarm", text: "Watch the swarm live — cards or the graph " +
    "view. Click a worker for its streaming cycle console."},
  {view: "clerk", text: "The clerk is your concierge: chat here to " +
    "steer rooms, or wire email/Telegram in settings for digests. " +
    "That's the loop — enjoy!"},
];

let tourIdx = -1;

function tourShow() {
  let box = $("tourBox");
  if (tourIdx < 0 || tourIdx >= TOUR_STEPS.length) {
    if (box) box.remove();
    if (tourIdx >= TOUR_STEPS.length) {
      localStorage.setItem("room_tpu_tour_done", "1");
    }
    return;
  }
  const step = TOUR_STEPS[tourIdx];
  if (currentView !== step.view) showView(step.view);
  if (!box) {
    box = document.createElement("div");
    box.id = "tourBox";
    box.className = "panel tour-box";
    document.body.appendChild(box);
  }
  box.innerHTML = `
    <div class="dim">setup guide · step ${tourIdx + 1}/` +
    `${TOUR_STEPS.length}</div>
    <div style="margin:.4rem 0">${esc(step.text)}</div>
    <div class="row" style="justify-content:flex-end">
      <button class="ghost" onclick="tourEnd()">skip</button>
      ${tourIdx > 0 ? `<button class="ghost"
        onclick="tourMove(-1)">back</button>` : ""}
      <button class="act" onclick="tourMove(1)">
        ${tourIdx === TOUR_STEPS.length - 1 ? "done" : "next"}</button>
    </div>`;
}

function tourStart() { tourIdx = 0; tourShow(); }
function tourMove(d) { tourIdx += d; tourShow(); }
function tourEnd() {
  tourIdx = TOUR_STEPS.length;
  tourShow();
}

const HELP_SECTIONS = [
  ["quickstart", "1. setup — create a room from a template or a " +
   "mission statement.\n2. providers — connect tpu:/claude:/codex:/" +
   "API models.\n3. rooms — start the room; the runtime wakes " +
   "workers on a cadence.\n4. swarm — watch cycles live; click a " +
   "worker for its console.\nRun the guided walkthrough any time " +
   "with the button above."],
  ["panels", "swarm: live worker cards + graph, streaming consoles\n" +
   "rooms: lifecycle, goals, credentials, quorum config, chat\n" +
   "setup: first-room wizard\nworkers: roster, prompts " +
   "export/import, manual trigger\ngoals: tree with progress " +
   "rollup\ntasks/runs: schedules (cron/once/watch) + run history\n" +
   "inbox: escalations to the keeper + inter-room mail\nvotes: " +
   "quorum ballots (worker + keeper votes)\nmemory: hybrid search " +
   "+ entity graph\nskills: reusable playbooks injected into " +
   "cycles\nwallet/transactions: balances, ERC-8004 identity, " +
   "signed transfers\ntpu: device gate, model provisioning, " +
   "capacity planner\ncycles: recent agent cycles with full logs\n" +
   "usage: per-provider token/cost rollups\nclerk: concierge chat\n" +
   "system: updates, watches, self-mod audit, invites\nsettings: " +
   "runtime knobs, provider logins, contacts"],
  ["keyboard + auth", "The dashboard reads the user token from the " +
   "localhost handshake automatically; paste it once for remote " +
   "browsers. Esc closes dialogs; Enter submits prompts."],
  ["agents", "Queens plan and delegate; workers execute cycles " +
   "against their goal queue; the clerk narrates and routes " +
   "keeper questions. Quiet hours, rotation and compression are " +
   "per-room settings."],
];

async function renderHelp(el) {
  el.innerHTML = `
    <div class="panel"><h2>help
      <button class="act" onclick="tourStart()">
        start guided walkthrough</button>
    </h2></div>
    ${HELP_SECTIONS.map(([title, body]) => `
      <div class="panel"><h2>${esc(title)}</h2>
        <pre style="white-space:pre-wrap;margin:0" class="dim">` +
        `${esc(body)}</pre>
      </div>`).join("")}`;
}

// ---- error boundary (reference: the SPA's per-panel ErrorBoundary
// components — one broken panel must not blank the app) ----

async function renderPanel(key, el) {
  const panel = PANELS[key];
  if (!panel || !el) return;
  try {
    await panel.render(el);
  } catch (e) {
    el.innerHTML = `<div class="panel">
      <h2>${esc(key)} failed to render</h2>
      <div class="dim">${esc(e && e.message || String(e))}</div>
      <div class="row">
        <button class="ghost" onclick="refreshView()">retry</button>
      </div></div>`;
  }
}

// ---- registry ----

const PANELS = {
  swarm: {title: "swarm", render: renderSwarm},
  rooms: {title: "rooms", render: renderRooms},
  setup: {title: "setup", render: renderSetup},
  workers: {title: "workers", render: renderWorkers},
  goals: {title: "goals", render: renderGoals},
  tasks: {title: "tasks", render: renderTasks},
  runs: {title: "runs", render: renderRuns},
  inbox: {title: "inbox", render: renderInbox},
  messages: {title: "messages", render: renderMessages},
  votes: {title: "votes", render: renderVotes},
  memory: {title: "memory", render: renderMemory},
  skills: {title: "skills", render: renderSkills},
  wallet: {title: "wallet", render: renderWallet},
  transactions: {title: "transactions", render: renderTransactions},
  tpu: {title: "tpu", render: renderTpu},
  cycles: {title: "cycles", render: renderCycles},
  usage: {title: "usage", render: renderUsage},
  providers: {title: "providers", render: renderProviders},
  clerk: {title: "clerk", render: renderClerk},
  status: {title: "status", render: renderStatus},
  feed: {title: "feed", render: renderFeed},
  system: {title: "system", render: renderSystem},
  settings: {title: "settings", render: renderSettings},
  help: {title: "help", render: renderHelp},
};
