/* room_tpu dashboard core: auth, fetch wrapper, WS hub, view router.
   Panels register themselves in PANELS (panels.js). */
"use strict";

let TOKEN = localStorage.getItem("room_tpu_token") || "";
let ws = null;
let currentView = localStorage.getItem("room_tpu_view") || "swarm";

const $ = (id) => document.getElementById(id);
const esc = (s) => String(s ?? "")
  .replaceAll("&", "&amp;").replaceAll("<", "&lt;")
  .replaceAll(">", "&gt;").replaceAll('"', "&quot;");
const when = (ts) => {
  if (!ts) return "";
  const d = typeof ts === "number" ? new Date(ts * 1000) : new Date(ts);
  return isNaN(d) ? String(ts) : d.toLocaleString();
};

async function tryHandshake() {
  // localhost-gated token mint; shared by boot() and the 401-retry
  try {
    const res = await fetch("/api/auth/handshake");
    const out = await res.json();
    if (out.data?.userToken) {
      TOKEN = out.data.userToken;
      localStorage.setItem("room_tpu_token", TOKEN);
      return true;
    }
  } catch {}
  return false;
}

async function api(method, path, body, retried) {
  const res = await fetch(path, {
    method,
    headers: {
      "Authorization": "Bearer " + TOKEN,
      ...(body ? {"Content-Type": "application/json"} : {}),
    },
    body: body ? JSON.stringify(body) : undefined,
  });
  if (res.status === 401) {
    // one silent refresh via the localhost handshake before bouncing
    // to the login screen (reference: ui/lib/client.ts 401-retry) —
    // a restarted server mints new tokens and the old one in
    // localStorage would otherwise strand every open tab
    if (!retried && await tryHandshake()) {
      return api(method, path, body, true);
    }
    showLogin();
    throw new Error("unauthorized");
  }
  const out = await res.json().catch(() => ({}));
  if (out.error && res.status >= 400) toast(out.error);
  return out;
}

function toast(text) {
  let el = $("toast");
  if (!el) {
    el = document.createElement("div");
    el.id = "toast";
    el.style.cssText = "position:fixed;bottom:1rem;right:1rem;" +
      "background:#3a2020;color:#ff9b9b;padding:.6rem .9rem;" +
      "border-radius:8px;z-index:50;max-width:40ch";
    document.body.appendChild(el);
  }
  el.textContent = text;
  el.style.display = "block";
  clearTimeout(el._t);
  el._t = setTimeout(() => { el.style.display = "none"; }, 5000);
}

function showLogin() {
  $("login").classList.remove("hidden");
  $("views").classList.add("hidden");
}

function saveToken() {
  TOKEN = $("tokenInput").value.trim();
  localStorage.setItem("room_tpu_token", TOKEN);
  boot();
}

// ---- view router ----

function buildNav() {
  $("nav").innerHTML = Object.keys(PANELS).map(key =>
    `<button data-view="${key}"` +
    `${key === currentView ? ' class="active"' : ""}>` +
    `${esc(PANELS[key].title)}</button>`).join("");
  $("nav").querySelectorAll("button").forEach(btn => {
    btn.onclick = () => showView(btn.dataset.view);
  });
  $("views").innerHTML = Object.keys(PANELS).map(key =>
    `<div id="view-${key}" class="hidden"></div>`).join("");
}

function showView(key) {
  currentView = key;
  localStorage.setItem("room_tpu_view", key);
  $("nav").querySelectorAll("button").forEach(b =>
    b.classList.toggle("active", b.dataset.view === key));
  Object.keys(PANELS).forEach(k =>
    $("view-" + k).classList.toggle("hidden", k !== key));
  refreshView();
}

function refreshView() {
  // renderPanel (panels.js) is the error boundary: a throwing panel
  // renders an inline error card with a retry button instead of
  // blanking the view
  renderPanel(currentView, $("view-" + currentView));
}

// ---- websocket ----

const subscribed = new Set();
function subscribe(channel) {
  if (ws && ws.readyState === 1 && !subscribed.has(channel)) {
    ws.send(JSON.stringify({type: "subscribe", channel}));
    subscribed.add(channel);
  }
}

function unsubscribe(channel) {
  if (ws && ws.readyState === 1 && subscribed.has(channel)) {
    ws.send(JSON.stringify({type: "unsubscribe", channel}));
    subscribed.delete(channel);
  }
}

async function subscribeRoomChannels() {
  // desktop notifications (escalation:created / decision:announced)
  // ride room:{id} channels: subscribe them ALL on boot and after
  // every reconnect, independent of which panel happens to render —
  // a keeper parked on another view must still get alerts. Belt and
  // braces with the "*" wildcard: explicit room subscriptions keep
  // notifications alive even if wildcard fan-out ever changes.
  try {
    const out = await api("GET", "/api/rooms");
    for (const r of out.data || []) subscribe(`room:${r.id}`);
  } catch {}
}

function connectWs() {
  ws = new WebSocket(
    `${location.protocol === "https:" ? "wss" : "ws"}://${location.host}` +
    `/ws?token=${encodeURIComponent(TOKEN)}`);
  ws.onopen = () => {
    subscribed.clear();
    ["*"].forEach(subscribe);
    subscribeRoomChannels();
  };
  ws.onmessage = (e) => {
    let msg;
    try { msg = JSON.parse(e.data); } catch { return; }
    if (msg.type === "subscribed" || msg.type === "unsubscribed") return;
    wsLog.push(msg);
    if (wsLog.length > 400) wsLog.shift();
    for (const fn of Object.values(wsHandlers)) {
      try { fn(msg); } catch {}
    }
  };
  ws.onclose = () => {
    $("statusline").textContent = "disconnected — retrying";
    setTimeout(connectWs, 3000);
  };
}

const wsLog = [];          // rolling event buffer for the feed panel
const wsHandlers = {};     // name -> fn(msg), panels register here

// ---- boot ----

async function boot() {
  if (!TOKEN) {
    await tryHandshake();
  }
  let st;
  try {
    st = await api("GET", "/api/status");
  } catch { return; }
  $("statusline").textContent =
    `v${st.data.version} · ${st.data.platform} x${st.data.devices}` +
    ` · ${st.data.activeRooms} rooms`;
  $("login").classList.add("hidden");
  $("views").classList.remove("hidden");
  buildNav();
  showView(currentView in PANELS ? currentView : "swarm");
  connectWs();
  setInterval(refreshView, 20000);
  registerServiceWorker(st.data.version);
  // first run, nothing configured yet: open the guided walkthrough
  if (!localStorage.getItem("room_tpu_tour_done") &&
      !(st.data.activeRooms > 0) && typeof tourStart === "function") {
    tourStart();
  }
}

// ---- dialog layer (reference: the SPA's ConfirmDialog/PromptDialog
// modal system — destructive actions must never fire on a stray
// click, and inputs should not ride window.prompt) ----

function _dialog({text, input, placeholder, okLabel}) {
  return new Promise((resolve) => {
    const wrap = document.createElement("div");
    wrap.className = "dialog-backdrop";
    wrap.innerHTML = `
      <div class="dialog panel" role="dialog" aria-modal="true">
        <div class="dialog-text">${esc(text)}</div>
        ${input ? `<input id="dialogInput"
          placeholder="${esc(placeholder || "")}"
          style="width:100%;margin:.5rem 0">` : ""}
        <div class="row" style="justify-content:flex-end">
          <button class="ghost" id="dialogCancel">cancel</button>
          <button class="act" id="dialogOk">
            ${esc(okLabel || "ok")}</button>
        </div>
      </div>`;
    document.body.appendChild(wrap);
    const done = (val) => { wrap.remove(); resolve(val); };
    wrap.querySelector("#dialogCancel").onclick =
      () => done(input ? null : false);
    wrap.querySelector("#dialogOk").onclick = () => done(
      input ? wrap.querySelector("#dialogInput").value : true);
    wrap.onclick = (e) => {
      if (e.target === wrap) done(input ? null : false);
    };
    wrap.addEventListener("keydown", (e) => {
      if (e.key === "Escape") done(input ? null : false);
      if (e.key === "Enter" && input) {
        done(wrap.querySelector("#dialogInput").value);
      }
    });
    const inp = wrap.querySelector("#dialogInput");
    if (inp) inp.focus();
    else wrap.querySelector("#dialogOk").focus();
  });
}

function confirmDialog(text, okLabel) {
  return _dialog({text, okLabel: okLabel || "confirm"});
}

function promptDialog(text, placeholder) {
  return _dialog({text, input: true, placeholder});
}


// ---- desktop notifications (reference: ui/lib/notifications.ts +
// useNotifications — browser alerts for escalations and new
// proposals, with a PWA app badge cleared on focus) ----

let notifyBadge = 0;

function notifySupported() {
  return "Notification" in window;
}

function notifyPermitted() {
  return notifySupported() && Notification.permission === "granted";
}

async function notifyRequest() {
  if (!notifySupported()) return false;
  const ok = (await Notification.requestPermission()) === "granted";
  refreshView();   // settings panel shows the new state
  return ok;
}

function setAppBadge(count) {
  notifyBadge = count;
  if (typeof navigator !== "undefined" && "setAppBadge" in navigator) {
    if (count > 0) navigator.setAppBadge(count).catch(() => {});
    else navigator.clearAppBadge().catch(() => {});
  }
}

function notifyShow(title, body) {
  // only alert when the tab can't be seen: a focused keeper is
  // already looking at the event
  if (!notifyPermitted() || !document.hidden) return;
  const n = new Notification(title, {body, icon: "/icon.svg"});
  n.onclick = () => { window.focus(); n.close(); };
  setAppBadge(notifyBadge + 1);
}

wsHandlers.notify = (msg) => {
  if (msg.type === "escalation:created") {
    notifyShow("keeper needed",
      (msg.data && msg.data.question) ||
      "an agent escalated a question to you");
  } else if (msg.type === "decision:announced") {
    notifyShow("new proposal",
      (msg.data && msg.data.proposal) || "a decision was announced");
  }
};

window.addEventListener("focus", () => setAppBadge(0));

// ---- PWA (reference: the SPA's service-worker layer) ----

function registerServiceWorker(version) {
  if (!("serviceWorker" in navigator)) return;
  navigator.serviceWorker.register("/sw.js").then((reg) => {
    // re-key the static cache per server version so an update-restart
    // invalidates stale assets; on updatefound the message must reach
    // the INSTALLING worker (reg.active is the old one)
    const post = (w) => {
      if (w) w.postMessage({type: "version", version});
    };
    post(reg.active || reg.waiting || reg.installing);
    reg.addEventListener("updatefound", () => post(reg.installing));
  }).catch(() => {});
}
