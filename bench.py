"""Round benchmark — prints ONE JSON line to stdout, incrementally.

Measures sustained decode throughput of the serving engine (continuous
batching + paged KV) on the qwen3-coder architecture scaled to fit a
single chip's HBM (same hidden/heads/GQA/qk-norm/MoE shape as the 30B
target; depth and expert count reduced). vs_baseline is measured against
the BASELINE.md north-star of 800 decode tok/s/chip.

Emission contract (VERDICT r4 #1 — the bench must not hold the headline
hostage to later phases):
  - The headline decode line (tok/s + MFU) is printed to stdout the
    moment phase 1 completes, then flushed. stdout carries exactly ONE
    JSON line either way (driver compatibility).
  - Every phase — decode, spec A/B, long-context prefill, latency,
    kernel compare, int8-KV A/B — appends its own JSON line to a side
    log (ROOM_TPU_BENCH_PHASES, default ./BENCH_PHASES.jsonl) as it
    completes, so a tunnel window that dies mid-run still leaves every
    finished phase on disk.
  - Each later phase is individually skippable via its env gate
    (ROOM_TPU_BENCH_SPEC/PREFILL/LATENCY/KVQ = 0).
  - The watchdog prints the 0.0 line and exits 1 only if the headline
    never appeared; once the headline is out, a hung later phase exits 0
    and the headline stands.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import threading
import time

BASELINE_TOK_S = 800.0
# first compile of the full bench model over the axon remote-compile
# tunnel runs >8 min cold; the watchdog must outlast it
WATCHDOG_S = float(os.environ.get("ROOM_TPU_BENCH_WATCHDOG_S", "1500"))
# CPU-proxy bench tier (ROADMAP): tiny model on the virtual CPU mesh,
# warm ROOM_TPU_JAX_CACHE, watchdog-sized — exercises the REAL engine
# paths and reports RELATIVE deltas (host_stall_ms_per_tok, TTFT by
# class, chunked-vs-monolithic prefill stall) so perf claims are
# falsifiable without hardware. BENCH_r01–r05 flat-lined at 0.0 from
# the TPU watchdog; this tier can never flat-line that way. The TPU
# headline stays the on-hardware tier.
CPU_PROXY = os.environ.get("ROOM_TPU_BENCH_CPU_PROXY") == "1"
TINY = os.environ.get("ROOM_TPU_BENCH_TINY") == "1" or CPU_PROXY
if CPU_PROXY:
    # the proxy tier must never touch (or wait on) a chip tunnel
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the dp_fused phase needs a multi-device mesh on the host (same
    # virtual-device trick the test tier uses); harmless for every
    # other phase, which keeps addressing device 0
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()

_result_printed = threading.Event()
_emit_lock = threading.Lock()
_bench_done = threading.Event()
_deadline = [0.0]  # extended before every long-running phase
_T0 = time.monotonic()
# phase breadcrumbs (weights loaded / compile done / first token):
# stamped as the run progresses AND attached to any 0.0 result line, so
# a watchdog-fired round is diagnosable (which stage never finished)
# instead of a silent zero (VERDICT r5: five consecutive 0.0 rounds).
_breadcrumbs: dict[str, float] = {}
# CPU-proxy relative deltas collected as phases complete (window-drain
# overlap, offload-restore latency, prefill stall, ragged dispatch
# delta) and emitted as one first-class `proxy_deltas` phase at the end
_proxy_deltas: dict[str, float] = {}


def _crumb(name: str) -> None:
    if name in _breadcrumbs:
        return
    _breadcrumbs[name] = round(time.monotonic() - _T0, 2)
    _phase("breadcrumb", {"name": name, "t_s": _breadcrumbs[name]})


def _extend_deadline() -> None:
    _deadline[0] = time.monotonic() + WATCHDOG_S


def _phase_log_path() -> str:
    return os.environ.get(
        "ROOM_TPU_BENCH_PHASES",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_PHASES.jsonl"),
    )


def _phase(name: str, payload) -> None:
    """Append one phase-result line to the side JSONL and flush; a
    tunnel that dies mid-bench leaves every completed phase on disk."""
    line = {"phase": name, "ts": round(time.time(), 1)}
    if isinstance(payload, dict):
        line.update(payload)
    else:
        line["result"] = payload
    try:
        with open(_phase_log_path(), "a") as f:
            f.write(json.dumps(line) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError as e:
        print(f"warning: phase log write failed: {e}", file=sys.stderr)


def acquire_chip_lock():
    """Cooperative exclusive chip lock (shared with scripts/tpu_watch.sh
    via /tmp/axon_chip.lock): two processes claiming the axon tunnel
    concurrently wedge it — the round 1-4 zero-bench root cause. Waits
    up to ROOM_TPU_CHIP_LOCK_WAIT_S (default 300 s) for a live holder
    (a watcher probe holds it <=600 s), then proceeds with a warning —
    the driver's end-of-round bench must not die on a stale holder.
    Returns the open fd (hold it for the process lifetime); None on
    CPU runs, which never touch the chip."""
    if TINY or os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        return None
    import fcntl

    fd = open(os.environ.get("ROOM_TPU_CHIP_LOCK",
                             "/tmp/axon_chip.lock"), "w")
    deadline = time.monotonic() + float(
        os.environ.get("ROOM_TPU_CHIP_LOCK_WAIT_S", "300")
    )
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return fd
        except OSError:
            if time.monotonic() > deadline:
                print("warning: chip lock still held after wait "
                      "budget; proceeding", file=sys.stderr)
                return fd
            time.sleep(5)


def _emit(value: float, unit: str, note: str = "",
          extra: dict | None = None) -> None:
    # lock makes check+set atomic: the watchdog firing at the same
    # instant main finishes must not put a second line on stdout
    with _emit_lock:
        if _result_printed.is_set():
            return
        _result_printed.set()
    line = {
        "metric": "decode_tok_per_s_per_chip",
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(value / BASELINE_TOK_S, 4),
    }
    if note:
        line["note"] = note
    if extra:
        line.update(extra)
    print(json.dumps(line), flush=True)


def decode_flops_per_token(cfg, mean_ctx: float) -> float:
    """Delegates to the canonical FLOPs model in
    room_tpu/perf/roofline.py so measured MFU (here) and predicted MFU
    share arithmetic. Imported lazily: a broken env must still reach
    main()'s try/except and emit the one 0.0 JSON line."""
    from room_tpu.perf.roofline import decode_flops_per_token as f

    return f(cfg, mean_ctx)


def _watchdog() -> None:
    _extend_deadline()
    while not _bench_done.is_set():
        now = time.monotonic()
        if now >= _deadline[0]:
            break
        time.sleep(min(_deadline[0] - now, 5.0))
    if _bench_done.is_set():
        return
    if not _result_printed.is_set():
        _emit(0.0, "tok/s",
              f"watchdog: no result after {WATCHDOG_S:.0f}s "
              "(TPU unreachable or compile exceeded the window; "
              "raise ROOM_TPU_BENCH_WATCHDOG_S)",
              extra={"breadcrumbs": dict(_breadcrumbs)})
        os._exit(1)
    # headline already on stdout: a hung later phase must not turn a
    # green decode measurement into a dead process
    _phase("watchdog_abort", {
        "note": f"later phase exceeded {WATCHDOG_S:.0f}s; "
                "headline decode line already emitted",
    })
    os._exit(0)


def bench_config():
    from room_tpu.models.config import DecoderConfig, tiny_moe

    if TINY:
        return tiny_moe()
    return DecoderConfig(
        name="qwen3-coder-bench",
        vocab_size=151_936,
        hidden=2048,
        n_layers=8,
        n_heads=32,
        n_kv_heads=4,
        head_dim=128,
        intermediate=0,
        rope_theta=1e7,
        qk_norm=True,
        n_experts=16,
        top_k=8,
        moe_intermediate=768,
        dtype="bfloat16",
    )


def _tpu_probe_or_proxy_fallback(jax_mod) -> None:
    """Driver fallback (ROADMAP item): when the TPU tunnel is
    unreachable, re-exec this bench as the CPU-proxy profile instead of
    letting the watchdog emit the 0.0 headline. jax.devices() runs in a
    worker thread with a bounded wait (ROOM_TPU_BENCH_TPU_PROBE_S,
    default 120 s) because a dead tunnel can hang backend init forever;
    a timeout, an init error, or a non-TPU platform all take the
    fallback. ROOM_TPU_BENCH_TPU_FALLBACK=0 restores the old
    fail-into-watchdog behavior."""
    if TINY:
        return   # CPU profiles never probe the chip
    if os.environ.get("ROOM_TPU_BENCH_TPU_FALLBACK", "1") == "0":
        return
    got: list = []

    def probe() -> None:
        try:
            got.append(jax_mod.devices()[0].platform)
        except Exception as e:  # noqa: BLE001 — any init error falls back
            got.append(f"error: {type(e).__name__}: {e}"[:200])

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(float(os.environ.get("ROOM_TPU_BENCH_TPU_PROBE_S", "120")))
    result = got[0] if got else "timeout"
    if result == "tpu":
        return
    _phase("tpu_unreachable_fallback", {
        "probe": result,
        "note": "TPU tunnel unreachable; re-running as the CPU-proxy "
                "profile (headline will carry profile=cpu_proxy)",
    })
    os.environ["ROOM_TPU_BENCH_CPU_PROXY"] = "1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    # fresh process: jax may already be mid-init against the dead
    # tunnel in the probe thread, which no in-process flag can undo
    os.execv(sys.executable,
             [sys.executable, os.path.abspath(__file__)] + sys.argv[1:])


def main() -> None:
    _chip_lock = acquire_chip_lock()  # noqa: F841 (held till exit)
    threading.Thread(target=_watchdog, daemon=True).start()

    import jax

    _tpu_probe_or_proxy_fallback(jax)

    if CPU_PROXY:
        # sitecustomize may have registered the TPU tunnel plugin and
        # snapshotted JAX_PLATFORMS before the env pin above — redirect
        # the config directly, same dance as tests/conftest.py
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    _crumb("jax_imported")

    # persistent compile cache (ROOM_TPU_JAX_CACHE): a warm run earlier
    # in the round turns the driver's end-of-round bench into cache
    # hits. The breadcrumb distinguishes a warm-start round from a
    # cold-compile one — BENCH_r01–r05 died inside the compile
    # watchdog with no way to tell which.
    from room_tpu.utils.compile_cache import enable_compile_cache

    cache_dir, cache_entries = enable_compile_cache()
    _phase("compile_cache", {
        "dir": cache_dir, "preexisting_entries": cache_entries,
    })
    if cache_entries:
        _crumb("compile_cache_hit")

    platform = jax.devices()[0].platform
    _phase("start", {"platform": platform, "tiny": TINY,
                     "watchdog_s": WATCHDOG_S})
    if platform != "cpu":
        # deep dispatch windows amortize host<->device round-trips (the
        # tunnel makes per-token syncs ruinous); greedy exactness across
        # window sizes is pinned in tests/test_decode_pipeline.py
        os.environ.setdefault("ROOM_TPU_DECODE_STEPS_PER_DISPATCH", "16")
    import jax.numpy as jnp

    from room_tpu.models import qwen3
    from room_tpu.serving import SamplingParams, ServingEngine

    # Headline operating point (VERDICT r5 "What's weak" #2): the
    # roofline grid says only int8-w+kv at batch 32 clears the 800
    # tok/s/chip baseline — measuring bf16/bs8 by default meant the
    # first green window would "fail" by configuration. Defaults are
    # env-overridable; explicitly setting ROOM_TPU_QUANT/KV_QUANT=""
    # opts a run back to bf16.
    if not TINY:
        os.environ.setdefault("ROOM_TPU_QUANT", "int8")
        os.environ.setdefault("ROOM_TPU_KV_QUANT", "int8")

    cfg = bench_config()
    # ROOM_TPU_MOE_IMPL=ragged|gshard|shardmap selects the MoE path so
    # the three implementations are benchable head-to-head (shardmap
    # builds a pure-ep mesh over all visible devices)
    moe_env = os.environ.get("ROOM_TPU_MOE_IMPL")
    if moe_env and cfg.is_moe:
        import dataclasses

        cfg = dataclasses.replace(cfg, moe_impl=moe_env)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    # ROOM_TPU_QUANT=int8 serves weight-only int8 (halves HBM bytes per
    # decode step — the bandwidth-bound path's main lever); int8 KV is
    # picked up by the engine itself from ROOM_TPU_KV_QUANT
    quant = os.environ.get("ROOM_TPU_QUANT") or None
    if quant:
        from room_tpu.ops.quant import (
            quantize_decoder_params, validate_quant_mode,
        )

        validate_quant_mode(quant)
        params = quantize_decoder_params(params, cfg)
    _crumb("weights_loaded")
    if cfg.moe_impl == "shardmap":
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from room_tpu.ops.moe_shardmap import set_ep_mesh

        devs = jax.devices()
        mesh = Mesh(np.array(devs).reshape(len(devs)), ("ep",))
        set_ep_mesh(mesh)
        for key in ("w_gate", "w_up", "w_down"):
            # device_put maps over pytrees, so a QTensor's q and s
            # (same rank, scale axis size-1) take the same spec
            params["layers"][key] = jax.device_put(
                params["layers"][key],
                NamedSharding(mesh, P(None, "ep", None, None)),
            )

    # batch 32 is the roofline's baseline-clearing operating point;
    # ROOM_TPU_BENCH_BATCH drops it back for A/B runs
    max_batch = 4 if TINY else int(
        os.environ.get("ROOM_TPU_BENCH_BATCH", "32")
    )
    prompt = list(range(1, 33))
    gen_timed = 32 if TINY else 256
    # greedy mode measures deterministic decoding (and makes any
    # speculative gains reproducible); default matches serving traffic
    greedy = os.environ.get("ROOM_TPU_BENCH_GREEDY") == "1"
    temp = 0.0 if greedy else 0.7
    top_p = 1.0 if greedy else 0.95

    def measure() -> tuple[float, int, float, dict]:
        eng = ServingEngine(
            cfg, params, max_batch=max_batch, page_size=32,
            n_pages=1024,
        )
        _crumb("engine_built")
        sp = SamplingParams(
            temperature=temp, top_p=top_p,
            max_new_tokens=16 if TINY else 64,
        )
        warm = [eng.submit(
            prompt, sampling=sp,
            # the first sampled token proves prefill compiled AND ran
            on_token=(lambda tok: _crumb("first_token")) if i == 0
            else None,
        ) for i in range(max_batch)]
        eng.run_until_idle()
        _crumb("compile_done")
        for t in warm:
            eng.release_session(t.session_id)
        start = eng.stats()
        for _ in range(max_batch * 2):
            eng.submit(prompt, sampling=SamplingParams(
                temperature=temp, top_p=top_p,
                max_new_tokens=gen_timed,
            ))
        t0 = time.perf_counter()
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        end = eng.stats()
        decoded = end["tokens_decoded"] - start["tokens_decoded"]
        # host-stall over the TIMED segment only (warmup compiles would
        # otherwise swamp the per-token figure)
        end["host_stall_ms_measured"] = round(
            end["host_stall_ms"] - start["host_stall_ms"], 3
        )
        return decoded / dt, decoded, dt, end

    from room_tpu.serving.kv_pages import use_pallas_kernel

    kernel_fallback = None
    try:
        tok_s, decoded, dt, eng_stats = measure()
    except Exception as e:
        # A Pallas lowering failure must not zero the round: retry on
        # the XLA gather path and report both facts. Only a run that
        # actually used the Pallas kernel qualifies.
        if not use_pallas_kernel():
            raise
        kernel_fallback = f"{type(e).__name__}: {e}"[:300]
    if kernel_fallback is not None:
        # retried outside the except block so the failed engine (and
        # its KV pool) isn't pinned by the live traceback during the
        # second attempt; give the retry its own full window
        os.environ["ROOM_TPU_PAGED_KERNEL"] = "xla"
        _extend_deadline()
        tok_s, decoded, dt, eng_stats = measure()

    # MFU estimate against the chip's peak bf16 matmul throughput
    # (override ROOM_TPU_PEAK_TFLOPS for the actual TPU generation;
    # default 197 = v5e bf16)
    peak_tflops = float(
        os.environ.get("ROOM_TPU_PEAK_TFLOPS", "197")
    )
    mean_ctx = len(prompt) + gen_timed / 2
    flops_tok = decode_flops_per_token(cfg, mean_ctx)
    mfu = tok_s * flops_tok / (peak_tflops * 1e12)

    extra = {
        "mfu": round(mfu, 4),
        "mfu_peak_tflops_assumed": peak_tflops,
        "flops_per_token": int(flops_tok),
        "batch": max_batch,
        # decode-pipeline visibility (docs/serving.md): ms the host
        # spent blocked on device drains per emitted token — the
        # quantity the multi-step window exists to shrink
        "steps_per_dispatch": eng_stats.get("steps_per_dispatch"),
        "host_stall_ms_per_tok": round(
            eng_stats.get("host_stall_ms_measured", 0.0)
            / max(decoded, 1), 4
        ),
    }
    if not TINY:
        # implied single-chip throughput on the full 30B target at the
        # measured MFU (decode is bandwidth/latency-bound, so this is an
        # optimistic ceiling, not a claim of 30B tok/s)
        from room_tpu.models.config import qwen3_coder_30b

        flops_full = decode_flops_per_token(qwen3_coder_30b(), mean_ctx)
        extra["implied_30b_tok_s_at_measured_mfu"] = round(
            mfu * peak_tflops * 1e12 / flops_full, 1
        )
    if CPU_PROXY:
        # mark proxy-tier lines loudly: the value is the RELATIVE
        # phase deltas, never a hardware throughput claim
        extra["profile"] = "cpu_proxy"
    if kernel_fallback:
        extra["pallas_error"] = kernel_fallback
        extra["kernel"] = "xla-fallback"
    if quant:
        extra["quant"] = quant
    if os.environ.get("ROOM_TPU_KV_QUANT"):
        extra["kv_quant"] = os.environ["ROOM_TPU_KV_QUANT"]
    spec_env = os.environ.get("ROOM_TPU_SPEC_TOKENS")
    if spec_env and spec_env != "0":
        # speculation engages only when contexts repeat (prompt-lookup
        # drafting); report what actually ran so a no-draft run can't
        # masquerade as a spec result
        extra["spec_tokens"] = int(spec_env)
        for k in ("spec_rounds", "spec_proposed", "spec_accepted"):
            extra[k] = eng_stats[k]

    # PHASE 1 COMPLETE — print the headline NOW. Four rounds of 0.0
    # taught that the headline must never wait on the remaining phases:
    # any green window >= warm-compile time yields this nonzero line.
    _emit(
        tok_s,
        "tok/s",
        f"{platform}; {cfg.name} bs={max_batch} "
        f"({decoded} tok / {dt:.1f}s)",
        extra=extra,
    )
    _phase("decode", {
        "tok_s": round(tok_s, 2), "decoded": decoded,
        "dt_s": round(dt, 2), "platform": platform, **extra,
    })

    # multi-step pipeline A/B: the dispatch-window win must be visible
    # even on CPU-only rounds — host_stall_ms_per_tok at steps=4 must
    # come in under steps=1 (the acceptance gate for the pipeline),
    # with tok/s riding along for the absolute picture
    if os.environ.get("ROOM_TPU_BENCH_PIPELINE", "1") != "0":
        prev_steps = os.environ.get("ROOM_TPU_DECODE_STEPS_PER_DISPATCH")
        ab: dict = {}
        try:
            for s in (1, 4):
                os.environ["ROOM_TPU_DECODE_STEPS_PER_DISPATCH"] = str(s)
                _extend_deadline()
                try:
                    s_tok, s_dec, _, s_stats = measure()
                    ab[f"steps{s}"] = {
                        "tok_s": round(s_tok, 2),
                        "host_stall_ms_per_tok": round(
                            s_stats.get("host_stall_ms_measured", 0.0)
                            / max(s_dec, 1), 4
                        ),
                    }
                except Exception as e:
                    ab[f"steps{s}"] = f"error: {e}"
        finally:
            if prev_steps is None:
                os.environ.pop(
                    "ROOM_TPU_DECODE_STEPS_PER_DISPATCH", None
                )
            else:
                os.environ["ROOM_TPU_DECODE_STEPS_PER_DISPATCH"] = \
                    prev_steps
        if isinstance(ab.get("steps1"), dict) and \
                isinstance(ab.get("steps4"), dict):
            # window-drain-overlap as a first-class proxy-tier number:
            # host-stall ms/tok the 4-deep window hides vs steps=1
            # (positive = the async drain overlapped that much)
            ab["window_drain_overlap_ms_per_tok"] = round(
                ab["steps1"]["host_stall_ms_per_tok"]
                - ab["steps4"]["host_stall_ms_per_tok"], 4
            )
            if CPU_PROXY:
                _proxy_deltas["window_drain_overlap_ms_per_tok"] = \
                    ab["window_drain_overlap_ms_per_tok"]
        _phase("decode_pipeline", ab)

    # speculative decoding A/B on agent-shaped traffic (VERDICT r2 #8):
    # tool-call JSON repetition is the motivating case — prompt-lookup
    # drafting only engages when context repeats, so generic prompts
    # can't measure it
    def measure_spec(spec_tokens: int) -> dict:
        eng = ServingEngine(
            cfg, params, max_batch=max_batch, page_size=32,
            n_pages=1024, spec_tokens=spec_tokens,
        )
        text = (
            '{"tool_call": {"name": "web_search", "arguments": '
            '{"query": "swarm status report"}}}\n'
        ) * (2 if TINY else 6)
        ptoks = eng.tokenizer.encode(text)
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=16 if TINY else 96,
        )
        warm = [eng.submit(ptoks, sampling=sp) for _ in range(max_batch)]
        eng.run_until_idle()
        for t in warm:
            eng.release_session(t.session_id)
        start = eng.stats()
        for _ in range(max_batch):
            eng.submit(ptoks, sampling=sp)
        t0 = time.perf_counter()
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        st = eng.stats()
        decoded = st["tokens_decoded"] - start["tokens_decoded"]
        out = {"tok_s": round(decoded / dt, 2)}
        if spec_tokens:
            proposed = st["spec_proposed"] - start["spec_proposed"]
            accepted = st["spec_accepted"] - start["spec_accepted"]
            out["proposed"] = proposed
            out["acceptance"] = round(accepted / max(proposed, 1), 3)
        return out

    if os.environ.get("ROOM_TPU_BENCH_SPEC", "1") != "0":
        for gamma in (0, 4):
            _extend_deadline()
            key = "off" if gamma == 0 else f"gamma{gamma}"
            try:
                _phase("spec_agent", {key: measure_spec(gamma)})
            except Exception as e:
                _phase("spec_agent", {key: f"error: {e}"})

    # in-window speculation x multi-step pipeline (docs/serving.md):
    # the fusion acceptance gate. On repetitive agent traffic at FULL
    # window depth, spec-on must emit > 1 token per device forward
    # with ZERO spec-induced window flushes (spec rounds ride inside
    # dispatches that still run the configured steps — the old path
    # composed every round as a steps=1 iteration) and pay no more
    # host stall per token than spec-off.
    def measure_spec_pipeline(spec_tokens: int) -> dict:
        eng = ServingEngine(
            cfg, params, max_batch=max_batch, page_size=32,
            n_pages=1024, spec_tokens=spec_tokens,
        )
        text = (
            '{"tool_call": {"name": "quorum_vote", "arguments": '
            '{"vote": "approve", "reasoning": "quorum boilerplate"}}}\n'
        ) * (2 if TINY else 6)
        ptoks = eng.tokenizer.encode(text)
        # long enough for greedy generation to settle into its loop —
        # prompt-lookup only drafts once the TAIL repeats, and the
        # trailing n-gram ends with generated tokens, not the prompt
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=48 if TINY else 96,
        )
        warm = [eng.submit(ptoks, sampling=sp) for _ in range(max_batch)]
        eng.run_until_idle()
        for t in warm:
            eng.release_session(t.session_id)
        start = eng.stats()
        for _ in range(max_batch):
            eng.submit(ptoks, sampling=sp)
        t0 = time.perf_counter()
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        st = eng.stats()
        decoded = st["tokens_decoded"] - start["tokens_decoded"]
        windows = st["decode_windows"] - start["decode_windows"]
        stall = st["host_stall_ms"] - start["host_stall_ms"]
        out = {
            "tok_s": round(decoded / dt, 2),
            # every dispatch runs `steps` scan forwards over the whole
            # batch: > 1 token per forward PER LANE is speculation
            # paying off inside the window (a non-drafting lane emits
            # exactly 1, and early stops only drag the ratio down)
            "tokens_per_forward": round(
                decoded / max(
                    windows * st["steps_per_dispatch"] * max_batch, 1
                ), 3
            ),
            "host_stall_ms_per_tok": round(
                stall / max(decoded, 1), 4
            ),
            "decode_windows": windows,
            "steps_per_dispatch": st["steps_per_dispatch"],
        }
        if spec_tokens:
            out["spec_rounds"] = \
                st["spec_rounds"] - start["spec_rounds"]
            proposed = st["spec_proposed"] - start["spec_proposed"]
            accepted = st["spec_accepted"] - start["spec_accepted"]
            out["acceptance"] = round(accepted / max(proposed, 1), 3)
        return out

    if os.environ.get("ROOM_TPU_BENCH_SPEC_PIPELINE", "1") != "0":
        # pin the window depth AND the gamma tuner for the A/B: live
        # adaptation (scheduler.SpecTuner) changes the compiled window
        # width mid-measurement, so on the CPU proxy a re-jit lands
        # inside the timed region and swamps the steady-state signal.
        # Adaptation itself is pinned by tests/test_scheduler.py.
        prev = {k: os.environ.get(k) for k in (
            "ROOM_TPU_DECODE_STEPS_PER_DISPATCH",
            "ROOM_TPU_SPEC_TUNE_EVERY",
        )}
        os.environ["ROOM_TPU_DECODE_STEPS_PER_DISPATCH"] = "4"
        os.environ["ROOM_TPU_SPEC_TUNE_EVERY"] = "1000000"
        ab = {}
        try:
            for gamma in (0, 4):
                _extend_deadline()
                key = "off" if gamma == 0 else f"gamma{gamma}"
                try:
                    ab[key] = measure_spec_pipeline(gamma)
                except Exception as e:
                    ab[key] = f"error: {e}"
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if isinstance(ab.get("off"), dict) and \
                isinstance(ab.get("gamma4"), dict):
            on = ab["gamma4"]
            # the no-flush evidence: spec rounds happened while every
            # dispatch still ran the full 4-step window (flushes would
            # surface as extra shallow windows, dragging per-lane
            # tokens_per_forward to <= 1)
            ab["flush_free"] = bool(
                on["spec_rounds"] > 0
                and on["steps_per_dispatch"] == 4
                and on["tokens_per_forward"] > 1.0
            )
            ab["stall_delta_ms_per_tok"] = round(
                on["host_stall_ms_per_tok"]
                - ab["off"]["host_stall_ms_per_tok"], 4
            )
            if CPU_PROXY:
                _proxy_deltas["spec_tokens_per_forward"] = \
                    on["tokens_per_forward"]
        _phase("spec_pipeline", ab)

    # long-context chunked prefill (VERDICT r2 #2's phase row): fresh
    # prefill of a long prompt, then a session continuation on top of
    # it — the continuation is the path whose page traffic must scale
    # with actual context (Pallas ragged prefill / bounded gather),
    # never the table's 32k capacity. ROOM_TPU_BENCH_CTX=32768 on
    # hardware with headroom.
    def measure_prefill(ctx: int) -> dict:
        n_pages = max(1024, (ctx + 4096) // 32 + 32)
        eng = ServingEngine(
            cfg, params, max_batch=2, page_size=32, n_pages=n_pages,
        )
        long_prompt = [1 + (i % 1000) for i in range(ctx)]
        one = SamplingParams(temperature=0.0, max_new_tokens=1)
        t0 = time.perf_counter()
        eng.submit(long_prompt, session_id="ctx", sampling=one)
        eng.run_until_idle()
        fresh_s = time.perf_counter() - t0
        # continuation: sessions take DELTA submission (the resumed
        # turn prefills only the new tokens on top of parked KV)
        t0 = time.perf_counter()
        eng.submit([2] * 256, session_id="ctx", sampling=one)
        eng.run_until_idle()
        cont_s = time.perf_counter() - t0
        return {
            "fresh_prefill_s": round(fresh_s, 3),
            "fresh_tok_per_s": round(ctx / fresh_s, 1),
            "continuation_256_s": round(cont_s, 3),
        }

    if os.environ.get("ROOM_TPU_BENCH_PREFILL", "1") != "0":
        ctxs = os.environ.get(
            "ROOM_TPU_BENCH_CTX", "512" if TINY else "4096,16384"
        )
        for ctx in (int(x) for x in ctxs.split(",") if x.strip()):
            _extend_deadline()
            try:
                _phase("long_context_prefill",
                       {f"ctx{ctx}": measure_prefill(ctx)})
            except Exception as e:
                _phase("long_context_prefill", {f"ctx{ctx}": f"error: {e}"})

    # queen-turn latency under swarm concurrency (BASELINE: p50 < 4 s
    # with 32 workers): concurrent queen-shaped turns against ONE
    # engine; queue wait beyond max_batch counts, as it does live
    def measure_latency(n_clients: int) -> dict:
        eng = ServingEngine(
            cfg, params, max_batch=max_batch, page_size=32,
            n_pages=1024,
        )
        stop = threading.Event()
        loop = threading.Thread(
            target=eng.serve_forever, args=(stop,), daemon=True,
        )
        loop.start()
        qprompt = list(range(1, 257))       # queen-cycle-sized context
        sp = SamplingParams(
            temperature=temp, top_p=top_p,
            max_new_tokens=16 if TINY else 64,
        )
        warm = eng.submit(qprompt, sampling=sp)
        warm.done.wait(WATCHDOG_S)
        eng.release_session(warm.session_id)
        lats: list[float] = []
        timeouts = [0]
        lock = threading.Lock()

        def client() -> None:
            t0 = time.perf_counter()
            turn = eng.submit(qprompt, sampling=sp)
            done = turn.done.wait(WATCHDOG_S)
            # timed-out turns must not blend the watchdog ceiling into
            # p50/p90, and their sessions must not leak for the rest of
            # the measurement
            try:
                eng.release_session(turn.session_id)
            except Exception:
                pass
            with lock:
                if done:
                    lats.append(time.perf_counter() - t0)
                else:
                    timeouts[0] += 1

        threads = [
            threading.Thread(target=client) for _ in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(WATCHDOG_S)
        stop.set()
        loop.join(30)
        lats.sort()
        out: dict = {}
        if lats:
            out["p50_s"] = round(lats[len(lats) // 2], 3)
            out["p90_s"] = round(lats[min(int(len(lats) * 0.9),
                                          len(lats) - 1)], 3)
        if timeouts[0]:
            out["timeouts"] = timeouts[0]
        return out

    if os.environ.get("ROOM_TPU_BENCH_LATENCY", "1") != "0":
        for n in ((4,) if TINY else (8, 32)):
            _extend_deadline()
            try:
                _phase("queen_turn_latency",
                       {f"clients{n}": measure_latency(n)})
            except Exception as e:
                _phase("queen_turn_latency", {f"clients{n}": f"error: {e}"})

    # tiered KV offload churn (docs/kv_offload.md): park a batch of
    # sessions, hibernate them all, resume them all — reports bytes
    # moved each way, restore latency, and the prefetch hit count, so
    # a round can see what a parked room costs to swap out and back
    def measure_offload() -> dict:
        n_sess = 4 if TINY else 8
        eng = ServingEngine(
            cfg, params, max_batch=4, page_size=32, n_pages=1024,
            offload=True,
        )
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=8 if TINY else 32,
        )
        for i in range(n_sess):
            eng.submit(prompt, session_id=f"off{i}", sampling=sp)
        eng.run_until_idle()
        # resident-resume baseline: the same continuation against KV
        # still in HBM — what the offload-restore latency is measured
        # RELATIVE to (first-class proxy-tier delta)
        t0 = time.perf_counter()
        for i in range(n_sess):
            eng.submit([9, 9, 9], session_id=f"off{i}", sampling=sp)
        eng.run_until_idle()
        resident_resume_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        n_off = sum(
            1 for i in range(n_sess)
            if eng.offload_session(f"off{i}")
        )
        offload_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for i in range(n_sess):
            eng.submit([2, 3, 4], session_id=f"off{i}", sampling=sp)
        eng.run_until_idle()
        resume_s = time.perf_counter() - t0
        st = eng.stats()
        ost = st["offload"]
        # offload-restore latency relative to the resident baseline
        # (positive = what hibernation adds to a resume)
        restore_delta = round(resume_s - resident_resume_s, 3)
        if CPU_PROXY:
            _proxy_deltas["offload_restore_latency_s"] = restore_delta
        return {
            "sessions": n_sess, "offloaded": n_off,
            "offload_s": round(offload_s, 3),
            "resume_s": round(resume_s, 3),
            "resident_resume_s": round(resident_resume_s, 3),
            "offload_restore_latency_s": restore_delta,
            "bytes_out": ost["bytes_out"],
            "bytes_in": ost["bytes_in"],
            "restores": st["offload_restores"],
            "prefetches": st["offload_prefetches"],
            "restore_ms_hist": ost["restore_ms_hist"],
        }

    if os.environ.get("ROOM_TPU_BENCH_OFFLOAD", "1") != "0":
        _extend_deadline()
        try:
            _phase("kv_offload", measure_offload())
        except Exception as e:
            _phase("kv_offload", {"error": str(e)[:300]})

    # warm-restart lifecycle (docs/lifecycle.md): drain a warm engine
    # to its manifest, boot a fresh one on the same weights, and
    # measure time-to-first-token on the resumed session. The restore
    # is a byte-exact KV memcpy and the persistent compile cache
    # (utils/compile_cache.py) covers the jit shapes, so the restart
    # tax should be milliseconds, not a re-prefill + recompile.
    def measure_warm_restart() -> dict:
        import shutil as _shutil
        import tempfile as _tempfile

        lc_dir = _tempfile.mkdtemp(prefix="room_tpu_bench_lc_")
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=8 if TINY else 32,
        )
        try:
            eng = ServingEngine(
                cfg, params, max_batch=4, page_size=32, n_pages=1024,
                offload=True,
            )
            eng.submit(prompt, session_id="wr", sampling=sp)
            eng.run_until_idle()
            t0 = time.perf_counter()
            drained = eng.drain(lc_dir)
            drain_s = time.perf_counter() - t0
            # drain spools sessions but does not free the device KV
            # cache — drop the engine before building its successor so
            # the phase keeps the one-engine-at-a-time memory footprint
            # every other phase has (two n_pages=1024 caches can OOM a
            # device sized near HBM capacity); the engine sits in
            # reference cycles (jit closures capture self), so del
            # alone leaves the KV pool to the cyclic GC's schedule
            del eng
            gc.collect()

            eng2 = ServingEngine(
                cfg, params, max_batch=4, page_size=32, n_pages=1024,
                offload=True,
            )
            t0 = time.perf_counter()
            restored = eng2.restore_from_manifest(lc_dir)
            restore_s = time.perf_counter() - t0
            first: dict = {}
            t0 = time.perf_counter()
            eng2.submit(
                [2, 3, 4], session_id="wr", sampling=sp,
                on_token=lambda tok: first.setdefault(
                    "t", time.perf_counter()
                ),
            )
            eng2.run_until_idle()
            return {
                "drain_s": round(drain_s, 3),
                "restore_s": round(restore_s, 3),
                # null, not phase-elapsed, when no token ever streamed:
                # a failed resume must not fabricate a plausible TTFT
                "ttft_after_restart_s": round(first["t"] - t0, 3)
                if "t" in first else None,
                "sessions_spooled": drained["sessions_spooled"],
                "sessions_resumed": restored["resumed"],
                "sessions_reprefill": restored["reprefill"],
            }
        finally:
            _shutil.rmtree(lc_dir, ignore_errors=True)

    if os.environ.get("ROOM_TPU_BENCH_RESTART", "1") != "0":
        _extend_deadline()
        try:
            _phase("warm_restart", measure_warm_restart())
        except Exception as e:
            _phase("warm_restart", {"error": str(e)[:300]})

    # fleet failover (docs/fleet.md): 3 replicas serving, kill the one
    # holding a mid-stream session, measure TTFT of the re-homed
    # continuation and assert zero durably-streamed tokens were lost
    # (the streamed prefix + the resumed stream must equal an unkilled
    # run). CPU-proxy-falsifiable like the scheduler A/B: the token-
    # loss count and re-home counters are real on any backend.
    def measure_fleet_failover() -> dict:
        from room_tpu.serving.fleet import EngineFleet

        budget = 24 if TINY else 48
        sp = SamplingParams(temperature=0.0, max_new_tokens=budget)
        small = SamplingParams(temperature=0.0, max_new_tokens=4)

        def build(i):
            return ServingEngine(
                cfg, params, max_batch=4, page_size=16, n_pages=512,
            )

        ctrl = ServingEngine(
            cfg, params, max_batch=4, page_size=16, n_pages=512,
        )
        cf = ctrl.submit(prompt, session_id="c", sampling=sp)
        ctrl.run_until_idle()
        full = list(cf.new_tokens)
        del ctrl
        gc.collect()

        fleet = EngineFleet(
            "bench", build, 3, auto_rebuild=False,
        )
        try:
            # warm pass: every replica compiles its shapes so the
            # failover TTFT measures re-homing, not XLA
            for h in fleet.replicas:
                h.engine.submit(prompt, session_id="warm",
                                sampling=small)
                h.engine.run_until_idle()
                h.engine.release_session("warm")
            streamed: list = []
            fleet.submit(prompt, session_id="s", sampling=sp,
                         on_token=streamed.append)
            bystanders = [
                fleet.submit(prompt, session_id=f"lane{i}",
                             sampling=small)
                for i in range(2)
            ]
            victim = fleet._handle(fleet._records["s"].rid)
            for _ in range(2000):
                victim.engine.step()
                if len(streamed) >= max(4, budget // 4):
                    break
            t0 = time.perf_counter()
            fleet.kill_replica(victim.rid, "bench failover")
            failover_s = time.perf_counter() - t0
            n = len(streamed)
            first: dict = {}
            t0 = time.perf_counter()
            t2 = fleet.submit(
                [], session_id="s",
                sampling=SamplingParams(
                    temperature=0.0, max_new_tokens=budget - n,
                ),
                on_token=lambda tok: first.setdefault(
                    "t", time.perf_counter()
                ),
            )
            fleet.run_until_idle()
            resumed = streamed + list(t2.new_tokens)
            token_loss = 0 if resumed == full else (
                len(full) - sum(
                    1 for a, b in zip(resumed, full) if a == b
                )
            )
            ttft = round(first["t"] - t0, 3) if "t" in first else None
            if CPU_PROXY and ttft is not None:
                _proxy_deltas["fleet_failover_ttft_s"] = ttft
            st = fleet.fleet_stats()
            return {
                "replicas": 3,
                "streamed_before_kill": n,
                "failover_s": round(failover_s, 3),
                # null, not phase-elapsed, when the resume never
                # streamed — a failed failover must not fabricate TTFT
                "ttft_after_failover_s": ttft,
                # the acceptance number: MUST be 0 — durably-streamed
                # tokens survive the kill and the continuation is
                # token-identical to the unkilled run
                "tokens_lost": token_loss,
                "sessions_rehomed": st["sessions_rehomed"],
                "rehomed_warm": st["sessions_rehomed_warm"],
                "bystanders_ok": sum(
                    1 for b in bystanders
                    if b.finish_reason == "length"
                ),
            }
        finally:
            del fleet
            gc.collect()

    if os.environ.get("ROOM_TPU_BENCH_FLEET", "1") != "0":
        _extend_deadline()
        try:
            _phase("fleet_failover", measure_fleet_failover())
        except Exception as e:
            _phase("fleet_failover", {"error": str(e)[:300]})

    # Pod partition failover (docs/podnet.md): partition the KV wire
    # mid-ship (wire_partition armed for every attempt), let
    # kv_wire_send exhaust its retry budget into the mirror
    # re-prefill degradation, and measure the first-token latency of
    # the continuation after the partition. The acceptance number is
    # tokens_lost == 0 — the partition may cost warmth, never tokens.
    def measure_partition_failover() -> dict:
        from room_tpu.serving import faults as faults_mod
        from room_tpu.serving import podnet as podnet_mod
        from room_tpu.serving.fleet import EngineFleet

        budget = 16 if TINY else 32
        sp = SamplingParams(temperature=0.0, max_new_tokens=budget)
        cont_sp = SamplingParams(temperature=0.0, max_new_tokens=8)
        cont = [7, 7, 7]
        ctrl = ServingEngine(
            cfg, params, max_batch=4, page_size=16, n_pages=512,
        )
        c1 = ctrl.submit(prompt, session_id="c", sampling=sp)
        ctrl.run_until_idle()
        c2 = ctrl.submit(cont, session_id="c", sampling=cont_sp)
        ctrl.run_until_idle()
        full, full2 = list(c1.new_tokens), list(c2.new_tokens)
        del ctrl
        gc.collect()

        # the wire knobs are read PER SEND, so they stay overridden
        # for the whole phase (restored in the outer finally)
        overrides = {
            "ROOM_TPU_DISAGG_WIRE": "loopback",
            "ROOM_TPU_DISAGG_PREFILL_TOKENS": "16",
            "ROOM_TPU_WIRE_RETRIES": "2",
            "ROOM_TPU_WIRE_BACKOFF_S": "0.005",
        }
        prev = {k: os.environ.get(k) for k in overrides}
        os.environ.update(overrides)

        def build(i):
            return ServingEngine(
                cfg, params, max_batch=4, page_size=16,
                n_pages=512, offload=True,
            )

        fleet = None
        try:
            fleet = EngineFleet(
                "bench-podnet", build, 2, auto_rebuild=False,
                roles=["prefill", "decode"],
            )
            for h in fleet.replicas:
                h.engine.submit(prompt, session_id="warm",
                                sampling=cont_sp)
                h.engine.run_until_idle()
                h.engine.release_session("warm")
            t1 = fleet.submit(prompt, session_id="s", sampling=sp)
            donor = fleet._handle(fleet._records["s"].rid)
            for _ in range(5000):
                donor.engine.step()
                if t1.done.is_set():
                    break
            # the partition lands NOW: the turn-boundary ship fires
            # into a dead wire, retries, exhausts, and degrades
            faults_mod.inject("wire_partition")
            fleet.supervise()
            wire_attempts = faults_mod.fired("wire_partition")
            faults_mod.clear("wire_partition")
            first: dict = {}
            t0 = time.perf_counter()
            t2 = fleet.submit(
                cont, session_id="s", sampling=cont_sp,
                on_token=lambda tok: first.setdefault(
                    "t", time.perf_counter()
                ),
            )
            fleet.run_until_idle()
            ttft = round(first["t"] - t0, 3) if "t" in first else None
            token_loss = 0
            if list(t1.new_tokens) != full or \
                    list(t2.new_tokens) != full2:
                token_loss = sum(
                    1 for a, b in zip(
                        list(t1.new_tokens) + list(t2.new_tokens),
                        full + full2,
                    ) if a != b
                ) or 1
            dst = fleet.fleet_stats()["disagg"]
            if CPU_PROXY and ttft is not None:
                _proxy_deltas["partition_failover_ttft_s"] = ttft
            return {
                "wire_attempts": wire_attempts,
                "wire_errors": dst["wire_errors"],
                "ships_reprefill": dst["ships_reprefill"],
                # the acceptance number: MUST be 0 — the exhausted
                # wire degrades to mirror re-prefill, token-identical
                "tokens_lost": token_loss,
                "ttft_after_partition_s": ttft,
                "breakers": podnet_mod.breakers_snapshot(),
            }
        finally:
            faults_mod.clear()
            if fleet is not None:
                fleet.disagg.close()
            podnet_mod.reset_breakers()
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            del fleet
            gc.collect()

    def measure_router_failover() -> dict:
        """Sharded router tier (docs/podnet.md): kill one of two
        router shards MID-STREAM, then prove (a) zero durably-streamed
        tokens lost — the victim room's engine session is untouched
        and every turn stays token-identical to an unkilled control,
        (b) the bystander shard's room never stalls, (c) after the
        sibling adopts the dead shard's journal, a submit carrying the
        pre-failover placement epoch is refused."""
        import shutil
        import tempfile

        from room_tpu.serving import faults as faults_mod
        from room_tpu.serving import podnet as podnet_mod
        from room_tpu.serving.fleet import EngineFleet

        budget = 16 if TINY else 32
        sp = SamplingParams(temperature=0.0, max_new_tokens=budget)
        cont_sp = SamplingParams(temperature=0.0, max_new_tokens=8)
        cont = [7, 7, 7]
        # two rooms that hash onto DIFFERENT router shards
        pm = podnet_mod.PlacementMap(2)
        sid_a = next(
            f"room-{i}" for i in range(64)
            if pm.shard_of(f"room-{i}") == 0
        )
        sid_b = next(
            f"room-{i}" for i in range(64)
            if pm.shard_of(f"room-{i}") == 1
        )
        ctrl = ServingEngine(
            cfg, params, max_batch=4, page_size=16, n_pages=512,
        )
        ref: dict[str, list] = {}
        for sid in (sid_a, sid_b):
            ref[sid] = []
            for turn_prompt, turn_sp in (
                (prompt, sp), (cont, cont_sp), (cont, cont_sp),
            ):
                t = ctrl.submit(
                    turn_prompt, session_id=sid, sampling=turn_sp,
                )
                ctrl.run_until_idle()
                ref[sid].append(list(t.new_tokens))
        del ctrl
        gc.collect()

        tmp = tempfile.mkdtemp(prefix="bench-router-")
        overrides = {
            "ROOM_TPU_ROUTER_SHARDS": "2",
            # effectively-infinite lease; the phase expires it by hand
            # so the dead window and the adoption are deterministic
            "ROOM_TPU_ROUTER_LEASE_S": "600",
            "ROOM_TPU_POD_MIRROR_BATCH": "1",
            "ROOM_TPU_LIFECYCLE_DIR": tmp,
        }
        prev = {k: os.environ.get(k) for k in overrides}
        os.environ.update(overrides)

        def build(i):
            return ServingEngine(
                cfg, params, max_batch=4, page_size=16,
                n_pages=512, offload=True,
            )

        fleet = None
        try:
            fleet = EngineFleet(
                "bench-router", build, 2, auto_rebuild=False,
            )
            got: dict[str, list] = {sid_a: [], sid_b: []}
            t1a = fleet.submit(prompt, session_id=sid_a, sampling=sp)
            t1b = fleet.submit(prompt, session_id=sid_b, sampling=sp)
            fleet.run_until_idle()
            got[sid_a].append(list(t1a.new_tokens))
            got[sid_b].append(list(t1b.new_tokens))
            # kill the victim's shard at sid_a's SECOND streamed token
            seen = {"n": 0}

            def killer(tok: int) -> None:
                seen["n"] += 1
                if seen["n"] == 2:
                    fleet.kill_router_shard(0, reason="bench")

            t2a = fleet.submit(
                cont, session_id=sid_a, sampling=cont_sp,
                on_token=killer,
            )
            fleet.run_until_idle()
            got[sid_a].append(list(t2a.new_tokens))
            # dead window: victim rooms shed, bystander rooms stream
            shed_probe = fleet.submit(
                cont, session_id=sid_a, sampling=cont_sp,
            )
            victim_shed = bool(shed_probe.shed)
            t2b = fleet.submit(
                cont, session_id=sid_b, sampling=cont_sp,
            )
            fleet.run_until_idle()
            got[sid_b].append(list(t2b.new_tokens))
            bystander_ok = not t2b.shed and \
                list(t2b.new_tokens) == ref[sid_b][1]
            # expire the lease by hand -> sibling adopts the journal
            stale_epoch = fleet.placement.epoch
            fleet.router_lease_s = 0.0
            fleet.supervise()
            rs = fleet.fleet_stats()["router_shards"]
            # a healed stale router replaying the pre-failover epoch
            stale_turn = fleet.submit(
                cont, session_id=sid_a, sampling=cont_sp,
                placement_epoch=stale_epoch,
            )
            stale_refused = bool(stale_turn.shed)
            first: dict = {}
            t0 = time.perf_counter()
            t3a = fleet.submit(
                cont, session_id=sid_a, sampling=cont_sp,
                on_token=lambda tok: first.setdefault(
                    "t", time.perf_counter()
                ),
            )
            t3b = fleet.submit(
                cont, session_id=sid_b, sampling=cont_sp,
            )
            fleet.run_until_idle()
            ttft = round(first["t"] - t0, 3) if "t" in first else None
            got[sid_a].append(list(t3a.new_tokens))
            got[sid_b].append(list(t3b.new_tokens))
            token_loss = sum(
                1 for sid in (sid_a, sid_b)
                for got_turn, ref_turn in zip(got[sid], ref[sid])
                for a, b in zip(got_turn, ref_turn)
                if a != b
            ) + sum(
                abs(len(got_turn) - len(ref_turn))
                for sid in (sid_a, sid_b)
                for got_turn, ref_turn in zip(got[sid], ref[sid])
            )
            if CPU_PROXY and ttft is not None:
                _proxy_deltas["router_failover_ttft_s"] = ttft
            return {
                # the acceptance numbers: tokens_lost MUST be 0, the
                # bystander shard's room must never stall, and the
                # stale epoch must be refused after the heal
                "tokens_lost": token_loss,
                "bystander_ok": bystander_ok,
                "victim_shed_during_lease": victim_shed,
                "stale_epoch_refused": stale_refused,
                "adoptions": rs["adoptions"],
                "sessions_adopted": rs["sessions_adopted"],
                "placement_epoch": rs["epoch"],
                "ttft_after_adoption_s": ttft,
            }
        finally:
            faults_mod.clear()
            if fleet is not None:
                fleet.disagg.close()
            podnet_mod.reset_breakers()
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            del fleet
            gc.collect()
            shutil.rmtree(tmp, ignore_errors=True)

    if os.environ.get("ROOM_TPU_BENCH_PODNET", "1") != "0":
        _extend_deadline()
        try:
            _phase("partition_failover", measure_partition_failover())
        except Exception as e:
            _phase("partition_failover", {"error": str(e)[:300]})
        _extend_deadline()
        try:
            _phase("router_failover", measure_router_failover())
        except Exception as e:
            _phase("router_failover", {"error": str(e)[:300]})

    # Swarm-shard storm (docs/swarmshard.md): 100+ rooms drive
    # journaled queen turns plus cross-room messages through the
    # room-partitioned swarm runtime, 1-shard vs 4-shard A/B on the
    # same workload. Each CPU-proxy queen turn is everything a real
    # cycle writes EXCEPT the model forward: journal started +
    # provider_call + a journaled effect + close, then one
    # message_send to another room. The sharded arm additionally eats
    # a mid-storm shard crash + sibling adoption and a duplicate
    # redispatch wave. Acceptance: 4-shard throughput beats 1-shard,
    # zero messages lost, zero double-fired effects.
    def measure_swarm_storm(n_shards: int) -> dict:
        import shutil
        import tempfile
        import threading as _threading

        from room_tpu.core import journal as journal_mod
        from room_tpu.swarm import SwarmRouter, shard_db_path

        n_rooms = int(
            os.environ.get("ROOM_TPU_BENCH_SWARM_ROOMS", "112")
        )
        cycles = int(
            os.environ.get("ROOM_TPU_BENCH_SWARM_CYCLES", "4")
        )
        n_threads = 8
        tmp = tempfile.mkdtemp(prefix=f"bench-swarm{n_shards}-")
        prev_stats = os.environ.get("ROOM_TPU_DB_LOCK_STATS")
        os.environ["ROOM_TPU_DB_LOCK_STATS"] = "1"
        router = None
        try:
            router = SwarmRouter(
                n_shards=n_shards, db_dir=tmp, lease_s=0.0,
            )
            rids = [
                router.create_room(f"storm-{i}")["id"]
                for i in range(n_rooms)
            ]
            # recall corpus: each room carries ~32 KB of notes, so a
            # turn's memory-recall scan reads the WHOLE shard file —
            # the per-shard working set (and the scan) shrinks with
            # the shard count, which is half the point of partitioning
            seed_body = "lorem swarm recall corpus " * 80
            for rid in rids:
                db = router.db_for(rid)
                with db.transaction():
                    for k in range(16):
                        db.execute(
                            "INSERT INTO room_messages(room_id, "
                            "direction, subject, body) VALUES "
                            "(?,'outbound',?,?)",
                            (rid, f"note {k}",
                             f"{seed_body} {rid} {k}"),
                        )
            sent: list[str] = []
            turn_s: list[float] = []
            # per-HOME turn latency: the per-shard p50/p95 columns
            # expose a hot shard hiding inside a healthy global p95
            shard_turn_s: dict[int, list[float]] = {
                k: [] for k in range(n_shards)
            }
            shed = {"n": 0}

            def one_turn(i: int, turn: int) -> None:
                """One CPU-proxy queen turn: everything a real cycle
                does around the model forward — memory-recall scan
                (context assembly), one journal transaction (started,
                provider_call, journaled effect, close), one
                message_send to another room."""
                rid = rids[i]
                db = router.db_for(rid)
                ref = rid * 10_000 + turn
                t0 = time.perf_counter()
                db.query_one(
                    "SELECT COUNT(*) AS n, SUM(LENGTH(body)) AS b "
                    "FROM room_messages WHERE body LIKE ?",
                    (f"%recall corpus%{turn}%",),
                )
                with db.transaction():
                    journal_mod.record_started(
                        db, "cycle", ref, room_id=rid,
                    )
                    journal_mod.record_provider_call(
                        db, "cycle", ref,
                        journal_mod.effect_key(
                            "cycle", rid, "turn", {"turn": turn}
                        ),
                        room_id=rid,
                    )
                    journal_mod.run_journaled_effect(
                        db, "cycle", ref, rid, None, "storm_note",
                        {"rid": rid, "turn": turn}, lambda: "noted",
                    )
                    journal_mod.record_finished(db, "cycle", ref)
                subject = f"storm {i}:{turn}"
                router.send_message(
                    rid, rids[(i + 17) % n_rooms], subject,
                    f"turn {turn} of room {rid}",
                )
                dt = time.perf_counter() - t0
                turn_s.append(dt)
                shard_turn_s[router.base_home(rid)].append(dt)
                sent.append(subject)

            def redispatch(i: int, turn: int) -> None:
                """Byte-identical duplicate of an already-delivered
                send (a healed caller replaying) — the journal's
                content-derived key must swallow it."""
                router.send_message(
                    rids[i], rids[(i + 17) % n_rooms],
                    f"storm {i}:{turn}",
                    f"turn {turn} of room {rids[i]}",
                )

            def storm(turns, crash_at=None) -> float:
                idx = {"n": 0}
                fails: list[tuple[int, int]] = []
                lock = _threading.Lock()

                def work():
                    while True:
                        with lock:
                            k = idx["n"]
                            if k >= len(turns):
                                return
                            idx["n"] = k + 1
                        i, turn = turns[k]
                        try:
                            one_turn(i, turn)
                        except Exception:
                            shed["n"] += 1
                            with lock:
                                fails.append((i, turn))

                t0 = time.perf_counter()
                threads = [
                    _threading.Thread(target=work, daemon=True)
                    for _ in range(n_threads)
                ]
                for t in threads:
                    t.start()
                if crash_at is not None:
                    while True:
                        with lock:
                            if idx["n"] >= crash_at:
                                break
                        time.sleep(0.002)
                    victim = max(
                        (s for s in router.shards
                         if s.state == "serving"),
                        key=lambda s: s.stats["rooms_created"],
                    )
                    router.kill_shard(
                        victim.shard_id, reason="bench storm"
                    )
                    router.adopt_dead_shards()
                for t in threads:
                    t.join()
                # whatever the crash window shed is replayed whole:
                # recovery flagged the committed halves, so the
                # journal swallows them and only the missing work
                # fires
                for i, turn in fails:
                    one_turn(i, turn)
                return time.perf_counter() - t0

            # timed clean section — the A/B numbers
            clean = [
                (i, t) for t in range(cycles) for i in range(n_rooms)
            ]
            elapsed = storm(clean)
            tput = round(len(clean) / max(elapsed, 1e-9), 1)
            ordered = sorted(turn_s)
            p50_ms = round(
                ordered[len(ordered) // 2] * 1e3, 3
            ) if ordered else None
            p95_ms = round(
                ordered[int(len(ordered) * 0.95)] * 1e3, 3
            ) if ordered else None
            lock_waits = sum(db.lock_waits for db in router.all_dbs())
            lock_wait_s = round(
                sum(db.lock_wait_s for db in router.all_dbs()), 4
            )
            # chaos section (untimed, multi-shard only): crash a
            # shard mid-storm, adopt, replay, then a duplicate
            # redispatch wave
            if n_shards > 1:
                chaos = [
                    (i, cycles + t) for t in range(2)
                    for i in range(n_rooms)
                ]
                storm(chaos, crash_at=len(chaos) // 2)
                for i, turn in [
                    (k % n_rooms, cycles + (k % 2))
                    for k in range(25)
                ]:
                    redispatch(i, turn)
            # exactly-once accounting across every shard file: each
            # logical subject must land exactly one inbound row
            delivered: dict[str, int] = {}
            for db in router.all_dbs():
                for row in db.query(
                    "SELECT subject, COUNT(*) AS n FROM room_messages "
                    "WHERE direction='inbound' AND "
                    "subject LIKE 'storm %' GROUP BY subject"
                ):
                    delivered[row["subject"]] = (
                        delivered.get(row["subject"], 0) + row["n"]
                    )
            unique_sent = set(sent)
            lost = sum(
                1 for s in unique_sent if delivered.get(s, 0) == 0
            )
            double_fired = sum(
                n - 1 for n in delivered.values() if n > 1
            )
            snap = router.snapshot()
            # per-shard columns: cycle-latency spread + on-disk
            # journal growth (file size is the durability bill the
            # shard paid for the storm)
            per_shard = []
            for k in range(n_shards):
                samples = sorted(shard_turn_s[k])
                try:
                    jbytes = os.path.getsize(shard_db_path(k, tmp))
                except OSError:
                    jbytes = 0
                per_shard.append({
                    "shard": k,
                    "turns": len(samples),
                    "turn_p50_ms": round(
                        samples[len(samples) // 2] * 1e3, 3
                    ) if samples else None,
                    "turn_p95_ms": round(
                        samples[int(len(samples) * 0.95)] * 1e3, 3
                    ) if samples else None,
                    "journal_bytes": jbytes,
                })
            if CPU_PROXY and n_shards == 1:
                _proxy_deltas["swarm_storm_1shard_tput"] = tput
            if CPU_PROXY and n_shards > 1:
                _proxy_deltas["swarm_storm_shard_p95_ms_max"] = max(
                    (s["turn_p95_ms"] or 0) for s in per_shard
                )
                _proxy_deltas["swarm_storm_journal_bytes_total"] = \
                    sum(s["journal_bytes"] for s in per_shard)
            return {
                "n_shards": n_shards,
                "rooms": n_rooms,
                "turns_timed": len(clean),
                "cycle_tput_per_s": tput,
                "queen_turn_p50_ms": p50_ms,
                "queen_turn_p95_ms": p95_ms,
                "journal_lock_waits": lock_waits,
                "journal_lock_wait_s": lock_wait_s,
                "messages_sent": len(unique_sent),
                "messages_lost": lost,
                "double_fired": double_fired,
                "shed_turns": shed["n"],
                "dedup_skips": snap["dedup_skips"],
                "shard_crashes": snap["shard_crashes"],
                "adoptions": snap["adoptions"],
                "placement_epoch": snap["placement"]["epoch"],
                "per_shard": per_shard,
            }
        finally:
            if router is not None:
                router.close()
            if prev_stats is None:
                os.environ.pop("ROOM_TPU_DB_LOCK_STATS", None)
            else:
                os.environ["ROOM_TPU_DB_LOCK_STATS"] = prev_stats
            del router
            gc.collect()
            shutil.rmtree(tmp, ignore_errors=True)

    if os.environ.get("ROOM_TPU_BENCH_SWARM", "1") != "0":
        _extend_deadline()
        one_shard = None
        try:
            one_shard = measure_swarm_storm(1)
            _phase("swarm_storm_1shard", one_shard)
        except Exception as e:
            _phase("swarm_storm_1shard", {"error": str(e)[:300]})
        _extend_deadline()
        try:
            four_shard = measure_swarm_storm(4)
            _phase("swarm_storm_4shard", four_shard)
            if one_shard and "cycle_tput_per_s" in one_shard:
                speedup = round(
                    four_shard["cycle_tput_per_s"]
                    / max(one_shard["cycle_tput_per_s"], 1e-9), 3,
                )
                if CPU_PROXY:
                    _proxy_deltas["swarm_storm_speedup"] = speedup
                _phase("swarm_storm_ab", {
                    # acceptance: speedup > 1.0, zero lost, zero
                    # double-fired — asserted by the CI smoke
                    "tput_1shard": one_shard["cycle_tput_per_s"],
                    "tput_4shard": four_shard["cycle_tput_per_s"],
                    "speedup": speedup,
                    "lock_waits_1shard":
                        one_shard["journal_lock_waits"],
                    "lock_waits_4shard":
                        four_shard["journal_lock_waits"],
                    "queen_turn_p50_ms_1shard":
                        one_shard["queen_turn_p50_ms"],
                    "queen_turn_p50_ms_4shard":
                        four_shard["queen_turn_p50_ms"],
                    "messages_lost":
                        one_shard["messages_lost"]
                        + four_shard["messages_lost"],
                    "double_fired":
                        one_shard["double_fired"]
                        + four_shard["double_fired"],
                    "shard_crashes": four_shard["shard_crashes"],
                    "adoptions": four_shard["adoptions"],
                })
        except Exception as e:
            _phase("swarm_storm_4shard", {"error": str(e)[:300]})

    # Process-mode swarm storm (docs/swarmshard.md "Process mode"):
    # the same cross-room message workload against (a) the in-process
    # 4-shard router and (b) 4 supervised shard child PROCESSES with
    # every dispatch riding a framed control-wire frame — including a
    # SIGKILL of one live child mid-storm (supervised restart +
    # journal replay), a byte-identical duplicate wave, and a
    # budget-exhaustion arm degrading to sibling adoption.
    # Acceptance: zero messages lost, zero double-fired, a restart
    # observed, the bystander shards' p95 unaffected, and the
    # exhausted-budget shard unhealthy after adoption.
    def measure_swarm_storm_proc() -> dict:
        import shutil
        import signal as _signal
        import tempfile
        import threading as _threading

        from room_tpu.db import Database
        from room_tpu.swarm import (
            ProcSupervisor, ShardDownError, SwarmRouter,
            shard_db_path,
        )

        n_rooms = int(os.environ.get(
            "ROOM_TPU_BENCH_SWARM_PROC_ROOMS", "112"
        ))
        waves = int(os.environ.get(
            "ROOM_TPU_BENCH_SWARM_PROC_WAVES", "2"
        ))
        n_threads = 8
        fast = dict(suspect_s=0.6, dead_s=1.2, lease_s=0.4,
                    backoff_s=0.05, hb_s=0.15)
        out: dict = {"n_shards": 4, "rooms": n_rooms,
                     "waves": waves}

        def run_sends(send, rids, tag, victim_home=None,
                      on_victim_pick=None):
            """Fire waves*n_rooms cross-room sends on 8 threads;
            returns (elapsed_s, all_lat, bystander_lat, fails)."""
            jobs = [(i, t) for t in range(waves)
                    for i in range(n_rooms)]
            idx = {"n": 0}
            lock = _threading.Lock()
            lat: list[tuple[float, bool]] = []
            fails: list[tuple[int, int]] = []

            def work():
                while True:
                    with lock:
                        k = idx["n"]
                        if k >= len(jobs):
                            return
                        idx["n"] = k + 1
                    i, t = jobs[k]
                    src, dst = rids[i], rids[(i + 17) % n_rooms]
                    t0 = time.perf_counter()
                    try:
                        send(src, dst, f"{tag} {i}:{t}",
                             f"wave {t} room {src}")
                    except Exception:
                        with lock:
                            fails.append((i, t))
                        continue
                    bystander = victim_home is None or (
                        victim_home["k"] is not None
                        and victim_home["k"] not in (
                            base_home(src), base_home(dst),
                        )
                    )
                    with lock:
                        lat.append(
                            (time.perf_counter() - t0, bystander)
                        )

            t0 = time.perf_counter()
            threads = [
                _threading.Thread(target=work, daemon=True)
                for _ in range(n_threads)
            ]
            for t in threads:
                t.start()
            if on_victim_pick is not None:
                while True:
                    with lock:
                        if idx["n"] >= len(jobs) // 3:
                            break
                    time.sleep(0.002)
                on_victim_pick()
            for t in threads:
                t.join()
            return time.perf_counter() - t0, lat, fails

        def pctl(samples, q):
            s = sorted(samples)
            return round(s[int(len(s) * q)] * 1e3, 3) if s else None

        # ---- arm A: the in-process 4-shard router, same workload
        tmp_a = tempfile.mkdtemp(prefix="bench-swarmproc-a-")
        router = None
        try:
            router = SwarmRouter(n_shards=4, db_dir=tmp_a,
                                 lease_s=0.0)
            base_home = router.base_home
            rids = [
                router.create_room(f"pstorm-{i}")["id"]
                for i in range(n_rooms)
            ]
            elapsed, lat, fails = run_sends(
                router.send_message, rids, "inproc"
            )
            assert not fails, fails[:3]
            out["inproc_send_tput_per_s"] = round(
                (waves * n_rooms) / max(elapsed, 1e-9), 1
            )
            out["inproc_send_p50_ms"] = pctl(
                [d for d, _ in lat], 0.5
            )
        finally:
            if router is not None:
                router.close()
            del router
            gc.collect()
            shutil.rmtree(tmp_a, ignore_errors=True)

        # ---- arm B: 4 shard child processes, crash mid-storm
        tmp_b = tempfile.mkdtemp(prefix="bench-swarmproc-b-")
        sup = None
        try:
            sup = ProcSupervisor(n_shards=4, db_dir=str(tmp_b),
                                 **fast)
            base_home = sup.base_home
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if all(c["state"] == "serving"
                       for c in sup.snapshot()["children"]):
                    break
                time.sleep(0.1)
            rids = [
                sup.create_room(f"pstorm-{i}")["id"]
                for i in range(n_rooms)
            ]
            stop = _threading.Event()

            def supervise_loop():
                while not stop.is_set():
                    sup.supervise()
                    time.sleep(0.05)

            sup_thread = _threading.Thread(
                target=supervise_loop, daemon=True
            )
            sup_thread.start()

            def send_retrying(src, dst, subject, body):
                give_up = time.monotonic() + 30
                while True:
                    try:
                        return sup.send_message(
                            src, dst, subject, body
                        )
                    except ShardDownError:
                        if time.monotonic() >= give_up:
                            raise
                        time.sleep(0.05)

            victim_home = {"k": None}

            def kill_one():
                live = [
                    c for c in sup.snapshot()["children"]
                    if c["state"] == "serving"
                    and c["pid"] is not None
                ]
                if not live:
                    return
                victim = max(live, key=lambda c: c["frames"])
                victim_home["k"] = victim["shard"]
                try:
                    os.kill(victim["pid"], _signal.SIGKILL)
                except ProcessLookupError:
                    pass

            elapsed, lat, fails = run_sends(
                send_retrying, rids, "pstorm",
                victim_home=victim_home, on_victim_pick=kill_one,
            )
            assert not fails, fails[:3]
            # the timed section EATS the crash: restart + shed
            # retries are inside this wall-clock, the bystander p95
            # is the sends that touched neither half of the victim
            out["proc_send_tput_per_s"] = round(
                (waves * n_rooms) / max(elapsed, 1e-9), 1
            )
            out["proc_send_p50_ms"] = pctl([d for d, _ in lat], 0.5)
            out["proc_send_p95_ms"] = pctl([d for d, _ in lat], 0.95)
            out["bystander_p95_ms"] = pctl(
                [d for d, by in lat if by], 0.95
            )
            out["victim_shard"] = victim_home["k"]
            # byte-identical duplicate wave: every one must dedup
            for k in range(25):
                i, t = k % n_rooms, k % waves
                send_retrying(
                    rids[i], rids[(i + 17) % n_rooms],
                    f"pstorm {i}:{t}", f"wave {t} room {rids[i]}",
                )
            out["restarts"] = sup.stats["restarts"]
            out["dedup_skips"] = sup.stats["dedup_skips"]
            stop.set()
            sup_thread.join(timeout=5)
            sup.stop()
            # exactly-once accounting straight off the shard files
            delivered: dict[str, int] = {}
            for k in range(4):
                db = Database(shard_db_path(k, str(tmp_b)))
                try:
                    for row in db.query(
                        "SELECT subject, COUNT(*) AS n FROM "
                        "room_messages WHERE direction='inbound' "
                        "AND subject LIKE 'pstorm %' "
                        "GROUP BY subject"
                    ):
                        delivered[row["subject"]] = (
                            delivered.get(row["subject"], 0)
                            + row["n"]
                        )
                finally:
                    db.close()
            expect = {
                f"pstorm {i}:{t}" for t in range(waves)
                for i in range(n_rooms)
            }
            out["messages_sent"] = len(expect)
            out["messages_lost"] = sum(
                1 for s in expect if delivered.get(s, 0) == 0
            )
            out["double_fired"] = sum(
                n - 1 for n in delivered.values() if n > 1
            )
        finally:
            if sup is not None:
                sup.stop()
            del sup
            gc.collect()
            shutil.rmtree(tmp_b, ignore_errors=True)

        # ---- arm C: restart budget exhausted -> sibling adoption
        tmp_c = tempfile.mkdtemp(prefix="bench-swarmproc-c-")
        sup = None
        try:
            sup = ProcSupervisor(n_shards=2, db_dir=str(tmp_c),
                                 restart_budget=0, **fast)
            base_home = sup.base_home
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if all(c["state"] == "serving"
                       for c in sup.snapshot()["children"]):
                    break
                time.sleep(0.1)
            rids = [
                sup.create_room(f"bstorm-{i}")["id"]
                for i in range(8)
            ]
            victim = sup.snapshot()["children"][1]
            if victim["pid"] is not None:
                os.kill(victim["pid"], _signal.SIGKILL)
            deadline = time.monotonic() + 25
            adoptions = []
            while time.monotonic() < deadline and not adoptions:
                adoptions = sup.supervise()
                time.sleep(0.05)
            out["budget_adoptions"] = len(adoptions)
            out["budget_unhealthy"] = sup.unhealthy_shards()
            # traffic keeps flowing through the adopter
            give_up = time.monotonic() + 20
            while True:
                try:
                    sup.send_message(rids[0], rids[1],
                                     "post-adopt", "x")
                    break
                except ShardDownError:
                    if time.monotonic() >= give_up:
                        raise
                    time.sleep(0.05)
            out["budget_post_adopt_send_ok"] = True
        finally:
            if sup is not None:
                sup.stop()
            del sup
            gc.collect()
            shutil.rmtree(tmp_c, ignore_errors=True)

        if CPU_PROXY:
            _proxy_deltas["swarm_storm_proc_tput"] = \
                out["proc_send_tput_per_s"]
            _proxy_deltas["swarm_storm_proc_wire_overhead"] = round(
                out["inproc_send_tput_per_s"]
                / max(out["proc_send_tput_per_s"], 1e-9), 3,
            )
        return out

    if os.environ.get("ROOM_TPU_BENCH_SWARM", "1") != "0" and \
            os.environ.get("ROOM_TPU_BENCH_SWARM_PROC", "1") != "0":
        _extend_deadline()
        try:
            _phase("swarm_storm_proc", measure_swarm_storm_proc())
        except Exception as e:
            _phase("swarm_storm_proc", {"error": str(e)[:300]})

    # Disaggregated prefill/decode A/B (docs/disagg.md): a burst of
    # 2k-token prompts against (a) a mixed fleet — every replica eats
    # prefill chunks between its decode windows — and (b) a
    # role-split fleet where the burst lands on the prefill replica
    # and queen turns on clean decode replicas. Plus the shared
    # prefix store's session-resume delta: a second engine admitting
    # the same system prefix pulls spooled KV instead of re-running
    # the prefill chunks.
    def measure_disagg_profile(roles) -> dict:
        from room_tpu.serving.fleet import EngineFleet

        bg_ctx = int(os.environ.get(
            "ROOM_TPU_BENCH_BG_CTX", "2048" if TINY else "4096"
        ))
        page_size = 16
        n_pages = max(1024, (bg_ctx * 4) // page_size + 256)
        prev = os.environ.get("ROOM_TPU_DISAGG_PREFILL_TOKENS")
        os.environ["ROOM_TPU_DISAGG_PREFILL_TOKENS"] = "256"

        def build(i):
            return ServingEngine(
                cfg, params, max_batch=4, page_size=page_size,
                n_pages=n_pages, offload=True,
            )

        try:
            fleet = EngineFleet(
                "bench-disagg", build, 3, auto_rebuild=False,
                roles=roles,
            )
        finally:
            if prev is None:
                os.environ.pop("ROOM_TPU_DISAGG_PREFILL_TOKENS", None)
            else:
                os.environ["ROOM_TPU_DISAGG_PREFILL_TOKENS"] = prev
        stop = threading.Event()
        loop = threading.Thread(
            target=fleet.serve_forever, args=(stop,), daemon=True,
        )
        loop.start()
        one = SamplingParams(temperature=0.0, max_new_tokens=2)
        qprompt = list(range(1, 33))

        def scenario(run: int) -> dict:
            # run 0 warms every replica's shape set so run 1 measures
            # routing + scheduling, not XLA compiles
            burst = [
                fleet.submit(
                    [2 + ((run * 7 + i) % 400)] * bg_ctx,
                    session_id=f"burst{run}_{i}",
                    sampling=one, turn_class="background",
                )
                for i in range(3)
            ]
            # wait until the burst's chunked prefills are actually in
            # flight — a queen submitted before that measures nothing
            base = fleet.stats().get("prefill_chunks_interleaved", 0)
            wait_until = time.perf_counter() + 10
            while time.perf_counter() < wait_until:
                if fleet.stats().get(
                    "prefill_chunks_interleaved", 0
                ) > base:
                    break
                time.sleep(0.002)
            first: dict = {}
            t0 = time.perf_counter()
            q = fleet.submit(
                qprompt, session_id=f"queen{run}", sampling=one,
                turn_class="queen",
                on_token=lambda tok: first.setdefault(
                    "t", time.perf_counter()),
            )
            q.done.wait(WATCHDOG_S)
            for b in burst:
                b.done.wait(WATCHDOG_S)
            if fleet.disagg.enabled:
                # let the turn-boundary KV ships land before the
                # sessions are released (the handoff is the thing
                # this phase exists to measure)
                base_ships = run * len(burst)
                wait_until = time.perf_counter() + 5
                while time.perf_counter() < wait_until:
                    if fleet.disagg.stats()["ships"] >= \
                            base_ships + len(burst):
                        break
                    time.sleep(0.01)
            for t in burst + [q]:
                fleet.release_session(t.session_id)
            return {
                "ttft": (first["t"] - t0) if "t" in first else None,
                "queen_finish": q.finish_reason,
                "queen_rid": getattr(q.trace, "rid", None),
            }

        try:
            scenario(0)
            _extend_deadline()
            m = scenario(1)
        finally:
            stop.set()
            loop.join(30)
            fleet.disagg.close()
        st = fleet.fleet_stats()
        out = {
            "roles": roles,
            "bg_ctx": bg_ctx,
            "queen_ttft_under_burst_s": round(m["ttft"], 4)
            if m["ttft"] is not None else None,
            "queen_finish": m["queen_finish"],
            "prefill_placements":
                st["disagg"].get("prefill_placements", 0),
            "ships": st["disagg"].get("ships", 0),
            "ships_warm": st["disagg"].get("ships_warm", 0),
        }
        del fleet
        gc.collect()
        return out

    def measure_prefix_store_resume() -> dict:
        import tempfile

        sys_ctx = 1024 if TINY else 2048
        sysp = [3 + (i % 350) for i in range(sys_ctx)]
        page_size = 16
        n_pages = max(512, (sys_ctx * 3) // page_size + 128)
        pfx_dir = tempfile.mkdtemp(prefix="room_tpu_bench_pfx_")
        prev_dir = os.environ.get("ROOM_TPU_PREFIX_STORE_DIR")
        prev_pages = os.environ.get("ROOM_TPU_PREFIX_CACHE_PAGES")
        os.environ["ROOM_TPU_PREFIX_STORE_DIR"] = pfx_dir
        os.environ.setdefault("ROOM_TPU_PREFIX_CACHE_PAGES", "2")

        def build(store: bool):
            return ServingEngine(
                cfg, params, max_batch=4, page_size=page_size,
                n_pages=n_pages, prefix_store=store,
            )

        def resume_cost(store: bool) -> dict:
            eng = build(store)
            t0 = time.perf_counter()
            t = eng.submit(sysp + [7, 8, 9], session_id="resume",
                           sampling=SamplingParams(
                               temperature=0.0, max_new_tokens=2))
            eng.run_until_idle()
            wall = time.perf_counter() - t0
            st = eng.stats()
            out = {
                "wall_s": round(wall, 4),
                "prefill_chunks":
                    st.get("prefill_chunks_interleaved", 0),
                "chunk_dispatches": st.get("chunk_dispatches", 0)
                + st.get("fused_chunks", 0),
                "store_hits": st.get("prefix_store_hits", 0),
                "finish": t.finish_reason,
            }
            del eng
            gc.collect()
            return out

        try:
            # publisher pass: computes + publishes the shared prefix
            pub = build(True)
            w = pub.submit(sysp + [5, 6], session_id="warm",
                           sampling=SamplingParams(
                               temperature=0.0, max_new_tokens=2))
            pub.run_until_idle()
            published = pub.stats().get("prefix_store_publishes", 0)
            del pub, w
            gc.collect()
            cold = resume_cost(False)   # re-prefills everything
            warm = resume_cost(True)    # pulls the published prefix
        finally:
            if prev_dir is None:
                os.environ.pop("ROOM_TPU_PREFIX_STORE_DIR", None)
            else:
                os.environ["ROOM_TPU_PREFIX_STORE_DIR"] = prev_dir
            if prev_pages is None:
                os.environ.pop("ROOM_TPU_PREFIX_CACHE_PAGES", None)
            else:
                os.environ["ROOM_TPU_PREFIX_CACHE_PAGES"] = prev_pages
            import shutil

            shutil.rmtree(pfx_dir, ignore_errors=True)
        return {
            "sys_ctx": sys_ctx,
            "published": published,
            "cold": cold,
            "warm": warm,
            # the acceptance number: chunk dispatches the store hit
            # removed from the resume re-prefill (must be > 0)
            "prefill_chunk_dispatch_delta":
                cold["prefill_chunks"] - warm["prefill_chunks"],
            "reprefill_wall_delta_s": round(
                cold["wall_s"] - warm["wall_s"], 4),
        }

    if os.environ.get("ROOM_TPU_BENCH_DISAGG", "1") != "0":
        ab = {}
        for label, roles in (
            ("mixed", ["mixed", "mixed", "mixed"]),
            ("roles", ["prefill", "decode", "decode"]),
        ):
            _extend_deadline()
            try:
                ab[label] = measure_disagg_profile(roles)
            except Exception as e:
                ab[label] = {"error": str(e)[:300]}
        if "error" not in ab.get("mixed", {}) and \
                "error" not in ab.get("roles", {}):
            mixed_ttft = ab["mixed"]["queen_ttft_under_burst_s"]
            roles_ttft = ab["roles"]["queen_ttft_under_burst_s"]
            # positive = role specialization protected that much
            # queen TTFT from the prompt burst
            ab["queen_ttft_delta_s"] = round(
                mixed_ttft - roles_ttft, 4
            ) if mixed_ttft is not None and roles_ttft is not None \
                else None
            if CPU_PROXY and ab["queen_ttft_delta_s"] is not None:
                _proxy_deltas["disagg_queen_ttft_delta_s"] = \
                    ab["queen_ttft_delta_s"]
        _extend_deadline()
        try:
            ab["prefix_store"] = measure_prefix_store_resume()
            if CPU_PROXY:
                _proxy_deltas["prefix_store_chunk_dispatch_delta"] = \
                    ab["prefix_store"]["prefill_chunk_dispatch_delta"]
        except Exception as e:
            ab["prefix_store"] = {"error": str(e)[:300]}
        _phase("disagg", ab)

    # SLO scheduler A/B (docs/scheduler.md): inject a multi-thousand-
    # token BACKGROUND prefill into a busy room (worker lanes decoding)
    # and land a QUEEN turn mid-prefill. Chunked interleave must bound
    # the queen's TTFT and the workers' inter-token stall; monolithic
    # (chunk pages 0) measures the head-of-line blocking it replaces.
    # This is the first bench claim falsifiable on the CPU-proxy tier.
    def measure_scheduler_profile(chunk_pages: int) -> dict:
        bg_ctx = int(os.environ.get(
            "ROOM_TPU_BENCH_BG_CTX", "2048" if TINY else "4096"
        ))
        n_workers = 2 if TINY else 6
        page_size = 16
        n_pages = max(1024, (bg_ctx * 3) // page_size + 256)
        prev = os.environ.get("ROOM_TPU_PREFILL_CHUNK_PAGES")
        os.environ["ROOM_TPU_PREFILL_CHUNK_PAGES"] = str(chunk_pages)
        try:
            eng = ServingEngine(
                cfg, params, max_batch=n_workers + 2,
                page_size=page_size, n_pages=n_pages,
            )
        finally:
            if prev is None:
                os.environ.pop("ROOM_TPU_PREFILL_CHUNK_PAGES", None)
            else:
                os.environ["ROOM_TPU_PREFILL_CHUNK_PAGES"] = prev
        stop = threading.Event()
        loop = threading.Thread(
            target=eng.serve_forever, args=(stop,), daemon=True,
        )
        loop.start()
        one = SamplingParams(temperature=0.0, max_new_tokens=2)
        gen = 64 if TINY else 128
        wprompt = list(range(1, 65))
        qprompt = list(range(1, 33))

        def scenario(run: int, bg_fill: int) -> dict:
            """Busy room + injected background prefill + queen turn.
            Run 0 is the warm pass — it walks the exact shape set
            (prefix-hit buckets, chunk widths, decode page buckets)
            so run 1 measures scheduling, not XLA compiles."""
            # clean-room queen TTFT (no background pressure)
            first: dict = {}
            t0 = time.perf_counter()
            q0 = eng.submit(
                qprompt, sampling=one, turn_class="queen",
                on_token=lambda tok: first.setdefault(
                    "t", time.perf_counter()),
            )
            q0.done.wait(WATCHDOG_S)
            eng.release_session(q0.session_id)
            # null, never a fabricated wait-elapsed, when no token
            # streamed (same contract as warm_restart's TTFT)
            ttft_clean = (first["t"] - t0) if "t" in first else None

            # worker lanes decoding; each lane's max inter-token gap
            # is the stall a monolithic prefill would cause
            gap = {"max": 0.0}
            last: dict = {}
            glock = threading.Lock()

            def lane_cb(lane):
                def cb(tok):
                    now = time.perf_counter()
                    with glock:
                        if lane in last:
                            gap["max"] = max(
                                gap["max"], now - last[lane]
                            )
                        last[lane] = now
                return cb

            wsp = SamplingParams(temperature=0.0, max_new_tokens=gen)
            workers = [
                eng.submit(wprompt, sampling=wsp, turn_class="worker",
                           session_id=f"lane{run}_{i}",
                           on_token=lane_cb(i))
                for i in range(n_workers)
            ]
            time.sleep(0.25)   # lanes decoding
            bg = eng.submit([bg_fill] * bg_ctx, sampling=one,
                            turn_class="background")
            # wait until the engine is actually INSIDE the background
            # admission (monolithic: mid-prefill; chunked: first
            # chunks written) — a queen submitted before that would
            # simply admit ahead of the not-yet-started prefill (EDF)
            # and measure no stall at all
            base_chunks = eng.stats()["prefill_chunks_interleaved"]
            wait_until = time.perf_counter() + 10
            while time.perf_counter() < wait_until and \
                    not bg.done.is_set():
                if bg.session_id in getattr(eng, "_admitting", ()) or \
                        eng.stats()["prefill_chunks_interleaved"] \
                        > base_chunks:
                    break
                time.sleep(0.002)
            first = {}
            t0 = time.perf_counter()
            q = eng.submit(
                qprompt, sampling=one, turn_class="queen",
                on_token=lambda tok: first.setdefault(
                    "t", time.perf_counter()),
            )
            q.done.wait(WATCHDOG_S)
            ttft_busy = (first["t"] - t0) if "t" in first else None
            bg.done.wait(WATCHDOG_S)
            for t in workers:
                t.done.wait(WATCHDOG_S)
            for t in workers + [bg, q]:
                eng.release_session(t.session_id)
            return {"ttft_clean": ttft_clean, "ttft_busy": ttft_busy,
                    "gap": gap["max"],
                    "queen_finish": q.finish_reason}

        try:
            scenario(0, 3)              # warm pass (compiles)
            _extend_deadline()
            m = scenario(1, 5)          # measured pass
            ttft_clean, ttft_busy = m["ttft_clean"], m["ttft_busy"]
            gap = {"max": m["gap"]}
        finally:
            stop.set()
            loop.join(30)
        st = eng.stats()
        sched = st.get("scheduler", {})
        ttft_by_class = {
            c: row.get("ttft_ema_s")
            for c, row in sched.get("classes", {}).items()
        }
        rnd = lambda v: round(v, 4) if v is not None else None  # noqa: E731
        out = {
            "chunk_pages": chunk_pages,
            "bg_ctx": bg_ctx,
            "queen_ttft_clean_s": rnd(ttft_clean),
            "queen_ttft_under_prefill_s": rnd(ttft_busy),
            # the acceptance number: how much a background prefill
            # degrades a queen turn (bounded under chunking); null —
            # with the finish_reason alongside — when the queen never
            # streamed, never a fabricated wait-elapsed
            "queen_ttft_degradation_s": rnd(
                ttft_busy - ttft_clean
                if ttft_busy is not None and ttft_clean is not None
                else None),
            "queen_finish": m["queen_finish"],
            "worker_max_gap_s": round(gap["max"], 4),
            "ttft_by_class": ttft_by_class,
            "prefill_chunks": st.get("prefill_chunks_interleaved", 0),
            "host_stall_ms_per_tok": round(
                st.get("host_stall_ms", 0.0)
                / max(st.get("tokens_decoded", 1), 1), 4),
        }
        del eng
        gc.collect()
        return out

    if os.environ.get("ROOM_TPU_BENCH_SCHED", "1") != "0":
        chunk_pages_ab = int(os.environ.get(
            "ROOM_TPU_BENCH_CHUNK_PAGES", "4" if TINY else "16"
        ))
        ab = {}
        for label, pages in (("chunked", chunk_pages_ab),
                             ("monolithic", 0)):
            _extend_deadline()
            try:
                ab[label] = measure_scheduler_profile(pages)
            except Exception as e:
                ab[label] = {"error": str(e)[:300]}
        if "error" not in ab.get("chunked", {}) and \
                "error" not in ab.get("monolithic", {}):
            # headline deltas: positive = chunking removed that much
            # stall (the chunked-vs-monolithic prefill-stall number)
            ab["prefill_stall_delta_s"] = round(
                ab["monolithic"]["worker_max_gap_s"]
                - ab["chunked"]["worker_max_gap_s"], 4)
            mono_ttft = ab["monolithic"]["queen_ttft_under_prefill_s"]
            chunk_ttft = ab["chunked"]["queen_ttft_under_prefill_s"]
            ab["queen_ttft_delta_s"] = round(
                mono_ttft - chunk_ttft, 4
            ) if mono_ttft is not None and chunk_ttft is not None \
                else None
            if CPU_PROXY:
                _proxy_deltas["prefill_stall_delta_s"] = \
                    ab["prefill_stall_delta_s"]
        _phase("scheduler", ab)

    # unified ragged fused-window A/B (docs/serving.md): split
    # per-chunk dispatches vs ONE fused dispatch per scheduler window,
    # bf16 and int8 KV. The dispatch-count delta is the CPU-proxy-tier
    # signal (each saved dispatch is a host round trip the TPU tunnel
    # pays for in full); wall-clock rides along.
    def measure_ragged(fused: bool, kv_quant) -> dict:
        prev_f = os.environ.get("ROOM_TPU_FUSED_WINDOW")
        prev_q = os.environ.get("ROOM_TPU_KV_QUANT")
        prev_c = os.environ.get("ROOM_TPU_PREFILL_CHUNK_PAGES")
        os.environ["ROOM_TPU_FUSED_WINDOW"] = "1" if fused else "0"
        # narrow chunks so the background prompt interleaves many of
        # them — the dispatch-count delta is the phase's whole point
        os.environ["ROOM_TPU_PREFILL_CHUNK_PAGES"] = "4"
        if kv_quant:
            os.environ["ROOM_TPU_KV_QUANT"] = kv_quant
        else:
            os.environ.pop("ROOM_TPU_KV_QUANT", None)
        try:
            eng = ServingEngine(
                cfg, params, max_batch=4, page_size=16, n_pages=1024,
            )
        finally:
            for name, prev in (
                ("ROOM_TPU_FUSED_WINDOW", prev_f),
                ("ROOM_TPU_KV_QUANT", prev_q),
                ("ROOM_TPU_PREFILL_CHUNK_PAGES", prev_c),
            ):
                if prev is None:
                    os.environ.pop(name, None)
                else:
                    os.environ[name] = prev
        bg_ctx = 512 if TINY else 2048
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=16 if TINY else 48,
        )
        one = SamplingParams(temperature=0.0, max_new_tokens=2)
        dprompt = list(range(1, 33))

        def traffic(fill: int):
            # decode lanes streaming while a long prompt chunk-prefills
            # between (or fused into) their windows
            lanes = [eng.submit(dprompt, sampling=sp) for _ in range(2)]
            bg = eng.submit([fill] * bg_ctx, sampling=one)
            eng.run_until_idle()
            for t in lanes + [bg]:
                eng.release_session(t.session_id)

        traffic(3)                       # warm pass (compiles)
        start = eng.stats()
        t0 = time.perf_counter()
        traffic(5)                       # measured pass
        dt = time.perf_counter() - t0
        st = eng.stats()
        out = {
            "wall_s": round(dt, 3),
            "chunk_dispatches": st["chunk_dispatches"]
            - start["chunk_dispatches"],
            "fused_windows": st["fused_windows"]
            - start["fused_windows"],
            "decode_windows": st["decode_windows"]
            - start["decode_windows"],
            "chunks": st["prefill_chunks_interleaved"]
            - start["prefill_chunks_interleaved"],
        }
        del eng
        gc.collect()
        return out

    if os.environ.get("ROOM_TPU_BENCH_RAGGED", "1") != "0":
        ragged_ab: dict = {}
        for qlabel, q in (("bf16", None), ("int8", "int8")):
            row: dict = {}
            for mode, fused_flag in (("split", False),
                                     ("unified", True)):
                _extend_deadline()
                try:
                    row[mode] = measure_ragged(fused_flag, q)
                except Exception as e:
                    row[mode] = {"error": str(e)[:300]}
            if isinstance(row.get("split"), dict) and \
                    "error" not in row["split"] and \
                    isinstance(row.get("unified"), dict) and \
                    "error" not in row["unified"]:
                # the acceptance number: device round trips the fused
                # window removed (positive = chunks rode the decode
                # dispatch instead of their own)
                row["dispatch_delta"] = (
                    row["split"]["chunk_dispatches"]
                    - row["unified"]["chunk_dispatches"]
                )
                row["wall_delta_s"] = round(
                    row["split"]["wall_s"] - row["unified"]["wall_s"],
                    3,
                )
                if CPU_PROXY:
                    _proxy_deltas[f"ragged_dispatch_delta_{qlabel}"] = \
                        row["dispatch_delta"]
            ragged_ab[qlabel] = row
        _phase("ragged_kernel", ragged_ab)

    # dp-sharded fused-window A/B (docs/serving.md): the fused window
    # used to auto-disable under dp sharding, paying one device call
    # per interleaved chunk; the sharded variant keeps chunks riding
    # the window as per-dp-shard ragged sub-batches. Three engines:
    # dp=1 fused (reference), dp=2 sharded-fused, dp=2 legacy-unfused
    # (ROOM_TPU_FUSED_WINDOW_DP=0). The acceptance number is
    # sharded-fused beating legacy-unfused on tok/s AND dispatches.
    def measure_dp_fused(dp: int, fused_dp: bool) -> dict:
        from room_tpu.parallel import (
            MeshSpec, decoder_param_specs, make_mesh, shard_pytree,
        )

        prev = {
            name: os.environ.get(name)
            for name in ("ROOM_TPU_FUSED_WINDOW",
                         "ROOM_TPU_FUSED_WINDOW_DP",
                         "ROOM_TPU_PREFILL_CHUNK_PAGES")
        }
        os.environ["ROOM_TPU_FUSED_WINDOW"] = "1"
        os.environ["ROOM_TPU_FUSED_WINDOW_DP"] = \
            "1" if fused_dp else "0"
        # one-page chunks: many interleaved chunks per background
        # prompt, so the legacy path's per-chunk device calls dominate
        os.environ["ROOM_TPU_PREFILL_CHUNK_PAGES"] = "1"
        try:
            kw = dict(max_batch=4, page_size=16, n_pages=1024)
            if dp > 1:
                mesh = make_mesh(MeshSpec(dp, 1, 1))
                sharded = shard_pytree(
                    params, decoder_param_specs(cfg), mesh
                )
                eng = ServingEngine(cfg, sharded, mesh=mesh, **kw)
            else:
                eng = ServingEngine(cfg, params, **kw)
        finally:
            for name, val in prev.items():
                if val is None:
                    os.environ.pop(name, None)
                else:
                    os.environ[name] = val
        bg_ctx = 512 if TINY else 2048
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=16 if TINY else 48,
        )
        one = SamplingParams(temperature=0.0, max_new_tokens=2)
        dprompt = list(range(1, 33))

        def traffic(fill: int) -> int:
            lanes = [eng.submit(dprompt, sampling=sp)
                     for _ in range(4)]
            bgs = [eng.submit([fill + i] * bg_ctx, sampling=one)
                   for i in range(2)]
            eng.run_until_idle()
            toks = sum(len(t.new_tokens) for t in lanes + bgs)
            for t in lanes + bgs:
                eng.release_session(t.session_id)
            return toks

        traffic(3)                       # warm pass (compiles)
        best = None
        for fill in (5, 7):              # best-of-2 measured passes
            start = eng.stats()
            t0 = time.perf_counter()
            toks = traffic(fill)
            dt = time.perf_counter() - t0
            st = eng.stats()
            disp = (
                st["decode_windows"] - start["decode_windows"]
                + st["chunk_dispatches"] - start["chunk_dispatches"]
            )
            row = {
                "tok_s": round(toks / dt, 2),
                "wall_s": round(dt, 3),
                "dispatches": disp,
                "dispatches_per_token": round(disp / max(1, toks), 3),
                "chunks": st["prefill_chunks_interleaved"]
                - start["prefill_chunks_interleaved"],
                "mode": eng.fused_window_mode,
            }
            if best is None or row["tok_s"] > best["tok_s"]:
                best = row
        del eng
        gc.collect()
        return best

    if os.environ.get("ROOM_TPU_BENCH_DP_FUSED", "1") != "0":
        dp_ab: dict = {}
        if len(jax.devices()) >= 2:
            for label, dp_n, flag in (("dp1_fused", 1, True),
                                      ("dp2_fused", 2, True),
                                      ("dp2_unfused", 2, False)):
                _extend_deadline()
                try:
                    dp_ab[label] = measure_dp_fused(dp_n, flag)
                except Exception as e:
                    dp_ab[label] = {"error": str(e)[:300]}
            sf, lu = dp_ab.get("dp2_fused"), dp_ab.get("dp2_unfused")
            if isinstance(sf, dict) and "error" not in sf and \
                    isinstance(lu, dict) and "error" not in lu:
                # the acceptance numbers: throughput won and device
                # round trips removed by keeping the window fused
                # under dp (positive = sharded-fused wins)
                dp_ab["tok_s_delta"] = round(
                    sf["tok_s"] - lu["tok_s"], 2
                )
                dp_ab["dispatch_delta"] = (
                    lu["dispatches"] - sf["dispatches"]
                )
                if CPU_PROXY:
                    _proxy_deltas["dp_fused_tok_s_delta"] = \
                        dp_ab["tok_s_delta"]
                    _proxy_deltas["dp_fused_dispatch_delta"] = \
                        dp_ab["dispatch_delta"]
        else:
            dp_ab["skipped"] = (
                f"needs >=2 devices, have {len(jax.devices())}"
            )
        _phase("dp_fused", dp_ab)

    # decode-attention backend comparison (Pallas paged kernel vs the
    # XLA gather reference) — only meaningful on real TPU hardware
    if platform == "tpu":
        backends = ("xla",) if kernel_fallback else ("pallas", "xla")
        for backend in backends:
            os.environ["ROOM_TPU_PAGED_KERNEL"] = backend
            _extend_deadline()
            try:
                b_tok_s, _, _, _ = measure()
                _phase("kernel_compare", {backend: round(b_tok_s, 2)})
            except Exception as e:
                _phase("kernel_compare", {backend: f"error: {e}"})
        if kernel_fallback:
            # Pallas is known-broken on this chip this run: later
            # phases (int8-KV A/B) must keep measuring the XLA path,
            # not re-hit the lowering failure
            os.environ["ROOM_TPU_PAGED_KERNEL"] = "xla"
        else:
            os.environ.pop("ROOM_TPU_PAGED_KERNEL", None)

        # int8 KV cache A/B (probe-gated kernels; falls back to the
        # bounded dequant gather if the lowering fails on this chip)
        if os.environ.get("ROOM_TPU_BENCH_KVQ", "1") != "0":
            os.environ["ROOM_TPU_KV_QUANT"] = "int8"
            _extend_deadline()
            try:
                kvq_tok_s, _, _, kvq_stats = measure()
                # record what actually ran: a probe-failed int8 kernel
                # silently measures the dequant gather, which must not
                # read as "int8 KV is slow"
                _phase("kv_quant_int8", {
                    "tok_s": round(kvq_tok_s, 2),
                    "backend": ("pallas" if kvq_stats.get("pallas_decode")
                                else "xla-dequant-gather"),
                })
            except Exception as e:
                _phase("kv_quant_int8", {"error": str(e)[:300]})
            os.environ.pop("ROOM_TPU_KV_QUANT", None)

    # turnscope A/B (docs/observability.md): tracing is always-on in
    # production, so its cost must be provably negligible — p50 turn
    # latency with the span recorder on vs off (interleaved passes so
    # thermal/jit drift doesn't bias one arm), plus a per-class SLO
    # attribution pass: a queen turn under a background prefill must
    # produce a span tree whose components cover its wall latency.
    def measure_trace_overhead() -> dict:
        from room_tpu.serving import trace as trace_mod

        eng = ServingEngine(
            cfg, params, max_batch=4, page_size=16, n_pages=512,
        )
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=16 if TINY else 32,
        )
        prompt = list(range(1, 33))
        lats: dict[bool, list] = {True: [], False: []}
        try:
            # warm pass walks the compile shapes for both arms
            for arm in (False, True):
                trace_mod.set_enabled(arm)
                t = eng.submit(prompt, sampling=sp)
                eng.run_until_idle()
                eng.release_session(t.session_id)
            reps = 8 if TINY else 12
            for _ in range(reps):
                for arm in (False, True):   # interleaved A/B
                    trace_mod.set_enabled(arm)
                    t0 = time.perf_counter()
                    t = eng.submit(prompt, sampling=sp)
                    eng.run_until_idle()
                    lats[arm].append(time.perf_counter() - t0)
                    eng.release_session(t.session_id)
        finally:
            trace_mod.set_enabled(None)
        p50 = {a: sorted(v)[len(v) // 2] for a, v in lats.items()}
        out = {
            "turns_per_arm": len(lats[True]),
            "p50_turn_off_s": round(p50[False], 5),
            "p50_turn_on_s": round(p50[True], 5),
            # the CI budget: trace-on p50 <= 5% over trace-off
            "overhead_ratio": round(p50[True] / max(p50[False], 1e-9),
                                    4),
        }
        del eng
        gc.collect()
        return out

    # invariant-witness A/B (docs/chaosfuzz.md): the witness probes
    # every engine.step() when armed, so production arming is only
    # viable if the probe cost is negligible — same interleaved-pass
    # shape as the turnscope A/B above, toggling ROOM_TPU_INVARIANTS
    # (strict off: measuring the probe, not the raise path)
    def measure_invariant_overhead() -> dict:
        from room_tpu.chaos import invariants as invariants_mod

        eng = ServingEngine(
            cfg, params, max_batch=4, page_size=16, n_pages=512,
        )
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=16 if TINY else 32,
        )
        prompt = list(range(1, 33))
        lats: dict[bool, list] = {True: [], False: []}
        saved = {
            k: os.environ.get(k)
            for k in ("ROOM_TPU_INVARIANTS",
                      "ROOM_TPU_INVARIANTS_STRICT")
        }
        os.environ["ROOM_TPU_INVARIANTS_STRICT"] = "0"

        def _arm(on: bool) -> None:
            os.environ["ROOM_TPU_INVARIANTS"] = "1" if on else "0"

        try:
            for arm in (False, True):   # warm pass for both arms
                _arm(arm)
                t = eng.submit(prompt, sampling=sp)
                eng.run_until_idle()
                eng.release_session(t.session_id)
            reps = 8 if TINY else 12
            for _ in range(reps):
                for arm in (False, True):   # interleaved A/B
                    _arm(arm)
                    t0 = time.perf_counter()
                    t = eng.submit(prompt, sampling=sp)
                    eng.run_until_idle()
                    lats[arm].append(time.perf_counter() - t0)
                    eng.release_session(t.session_id)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            invariants_mod.reset()
        p50 = {a: sorted(v)[len(v) // 2] for a, v in lats.items()}
        out = {
            "turns_per_arm": len(lats[True]),
            "p50_turn_off_s": round(p50[False], 5),
            "p50_turn_on_s": round(p50[True], 5),
            # the CI budget: witness-on p50 <= 5% over witness-off
            "overhead_ratio": round(p50[True] / max(p50[False], 1e-9),
                                    4),
        }
        del eng
        gc.collect()
        return out

    def measure_slo_attribution() -> dict:
        from room_tpu.serving import trace as trace_mod

        bg_ctx = 2048
        trace_mod.set_enabled(True)
        trace_mod.recorder.reset()
        prev = os.environ.get("ROOM_TPU_PREFILL_CHUNK_PAGES")
        os.environ["ROOM_TPU_PREFILL_CHUNK_PAGES"] = "4"
        try:
            eng = ServingEngine(
                cfg, params, max_batch=4, page_size=16,
                n_pages=max(1024, (bg_ctx * 3) // 16 + 256),
            )
        except BaseException:
            # a failed engine build must not leak the force-enabled
            # override into later phases
            trace_mod.set_enabled(None)
            raise
        finally:
            if prev is None:
                os.environ.pop("ROOM_TPU_PREFILL_CHUNK_PAGES", None)
            else:
                os.environ["ROOM_TPU_PREFILL_CHUNK_PAGES"] = prev
        stop = threading.Event()
        loop = threading.Thread(
            target=eng.serve_forever, args=(stop,), daemon=True,
        )
        loop.start()
        one = SamplingParams(temperature=0.0, max_new_tokens=2)
        wsp = SamplingParams(
            temperature=0.0, max_new_tokens=32 if TINY else 64,
        )
        try:
            # warm pass (compiles)
            w = eng.submit(list(range(1, 65)), sampling=wsp,
                           turn_class="worker")
            b = eng.submit([3] * bg_ctx, sampling=one,
                           turn_class="background")
            q = eng.submit(list(range(1, 33)), sampling=one,
                           turn_class="queen")
            for t in (w, b, q):
                t.done.wait(WATCHDOG_S)
                eng.release_session(t.session_id)
            _extend_deadline()
            # measured pass: queen lands mid-background-prefill
            workers = [
                eng.submit(list(range(1, 65)), sampling=wsp,
                           session_id=f"attr_lane{i}",
                           turn_class="worker")
                for i in range(2)
            ]
            time.sleep(0.2)
            bg = eng.submit([5] * bg_ctx, sampling=one,
                            turn_class="background")
            time.sleep(0.05)   # background admission under way
            queen = eng.submit(list(range(1, 33)), sampling=one,
                               turn_class="queen")
            for t in workers + [bg, queen]:
                t.done.wait(WATCHDOG_S)
                eng.release_session(t.session_id)
            qt = queen.trace.to_dict() if queen.trace else {}
        finally:
            stop.set()
            loop.join(30)
            trace_mod.set_enabled(None)
        spans = qt.get("spans", {})
        covered = (spans.get("queue_ms", 0.0)
                   + spans.get("prefill_ms", 0.0)
                   + spans.get("decode_ms", 0.0))
        attribution = trace_mod.recorder.attribution()
        out = {
            "bg_ctx": bg_ctx,
            "queen_trace": qt,
            # the acceptance number: top-level spans must cover the
            # measured wall latency (docs/observability.md)
            "queen_span_coverage": round(
                covered / max(spans.get("wall_ms", 1e-9), 1e-9), 4),
            "classes": attribution.get("classes", {}),
        }
        del eng
        gc.collect()
        return out

    if os.environ.get("ROOM_TPU_BENCH_TRACE", "1") != "0":
        _extend_deadline()
        try:
            overhead = measure_trace_overhead()
            _phase("trace_overhead", overhead)
            if CPU_PROXY:
                _proxy_deltas["trace_overhead_ratio"] = \
                    overhead["overhead_ratio"]
        except Exception as e:
            _phase("trace_overhead", {"error": str(e)[:300]})
        _extend_deadline()
        try:
            inv_overhead = measure_invariant_overhead()
            _phase("invariant_overhead", inv_overhead)
            if CPU_PROXY:
                _proxy_deltas["invariant_overhead_ratio"] = \
                    inv_overhead["overhead_ratio"]
        except Exception as e:
            _phase("invariant_overhead", {"error": str(e)[:300]})
        _extend_deadline()
        try:
            _phase("slo_attribution", measure_slo_attribution())
        except Exception as e:
            _phase("slo_attribution", {"error": str(e)[:300]})

    if CPU_PROXY and _proxy_deltas:
        # first-class proxy-tier numbers (ROADMAP item): the relative
        # deltas a hardware-free round can still falsify
        _phase("proxy_deltas", dict(_proxy_deltas))

    _phase("bench_complete", {"headline_tok_s": round(tok_s, 2)})
    _bench_done.set()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # the one JSON line must always appear
        if _result_printed.is_set():
            # headline already on stdout; a later-phase crash must not
            # turn the run into a failure
            _phase("error_after_headline",
                   {"error": f"{type(e).__name__}: {e}"[:300]})
            sys.exit(0)
        _emit(0.0, "tok/s", f"error: {type(e).__name__}: {e}",
              extra={"breadcrumbs": dict(_breadcrumbs)})
        sys.exit(1)
