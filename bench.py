"""Round benchmark — prints ONE JSON line.

Measures sustained decode throughput of the serving engine (continuous
batching + paged KV) on the qwen3-coder architecture scaled to fit a
single chip's HBM (same hidden/heads/GQA/qk-norm/MoE shape as the 30B
target; depth and expert count reduced). vs_baseline is measured against
the BASELINE.md north-star of 800 decode tok/s/chip.

A watchdog guarantees the JSON line is printed even if the TPU tunnel is
unreachable.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

BASELINE_TOK_S = 800.0
WATCHDOG_S = float(os.environ.get("ROOM_TPU_BENCH_WATCHDOG_S", "480"))
TINY = os.environ.get("ROOM_TPU_BENCH_TINY") == "1"  # CPU smoke mode

_result_printed = threading.Event()


def _emit(value: float, unit: str, note: str = "") -> None:
    if _result_printed.is_set():
        return
    _result_printed.set()
    line = {
        "metric": "decode_tok_per_s_per_chip",
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(value / BASELINE_TOK_S, 4),
    }
    if note:
        line["note"] = note
    print(json.dumps(line), flush=True)


def _watchdog() -> None:
    time.sleep(WATCHDOG_S)
    if not _result_printed.is_set():
        _emit(0.0, "tok/s", "watchdog: TPU backend unreachable")
        os._exit(1)


def bench_config():
    from room_tpu.models.config import DecoderConfig, tiny_moe

    if TINY:
        return tiny_moe()
    return DecoderConfig(
        name="qwen3-coder-bench",
        vocab_size=151_936,
        hidden=2048,
        n_layers=8,
        n_heads=32,
        n_kv_heads=4,
        head_dim=128,
        intermediate=0,
        rope_theta=1e7,
        qk_norm=True,
        n_experts=16,
        top_k=8,
        moe_intermediate=768,
        dtype="bfloat16",
    )


def main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()

    import jax

    platform = jax.devices()[0].platform
    if platform != "cpu":
        # amortize host<->device round-trips (the tunnel makes per-token
        # syncs ruinous); exact-equivalence is pinned in tests
        os.environ.setdefault("ROOM_TPU_DECODE_CHUNK", "16")
    import jax.numpy as jnp

    from room_tpu.models import qwen3
    from room_tpu.serving import SamplingParams, ServingEngine

    cfg = bench_config()
    # ROOM_TPU_MOE_IMPL=ragged|gshard|shardmap selects the MoE path so
    # the three implementations are benchable head-to-head (shardmap
    # builds a pure-ep mesh over all visible devices)
    moe_env = os.environ.get("ROOM_TPU_MOE_IMPL")
    if moe_env and cfg.is_moe:
        import dataclasses

        cfg = dataclasses.replace(cfg, moe_impl=moe_env)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    if cfg.moe_impl == "shardmap":
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from room_tpu.ops.moe_shardmap import set_ep_mesh

        devs = jax.devices()
        mesh = Mesh(np.array(devs).reshape(len(devs)), ("ep",))
        set_ep_mesh(mesh)
        for key in ("w_gate", "w_up", "w_down"):
            params["layers"][key] = jax.device_put(
                params["layers"][key],
                NamedSharding(mesh, P(None, "ep", None, None)),
            )

    max_batch = 4 if TINY else 8
    eng = ServingEngine(
        cfg, params, max_batch=max_batch, page_size=32, n_pages=1024
    )

    gen_tokens = 16 if TINY else 64
    sp = SamplingParams(
        temperature=0.7, top_p=0.95, max_new_tokens=gen_tokens
    )
    prompt = list(range(1, 33))

    # warmup: compile prefill + decode
    warm = [eng.submit(prompt, sampling=sp) for _ in range(max_batch)]
    eng.run_until_idle()
    for t in warm:
        eng.release_session(t.session_id)

    # timed: keep all slots busy; count decoded tokens over the window
    start_stats = eng.stats()
    turns = [
        eng.submit(prompt, sampling=SamplingParams(
            temperature=0.7, top_p=0.95,
            max_new_tokens=32 if TINY else 256,
        ))
        for _ in range(max_batch * 2)
    ]
    t0 = time.perf_counter()
    eng.run_until_idle()
    dt = time.perf_counter() - t0
    end_stats = eng.stats()

    decoded = end_stats["tokens_decoded"] - start_stats["tokens_decoded"]
    tok_s = decoded / dt
    _emit(
        tok_s,
        "tok/s",
        f"{platform}; {cfg.name} bs={max_batch} "
        f"({decoded} tok / {dt:.1f}s)",
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # the one JSON line must always appear
        _emit(0.0, "tok/s", f"error: {type(e).__name__}: {e}")
        sys.exit(1)
